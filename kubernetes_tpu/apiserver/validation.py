"""API object validation — the pkg/api/validation analog, enforced on the
store's write path the way the registry strategies run Validate before
storage (apiserver/pkg/registry/generic/registry/store.go Create).

Covers the invariants the control plane itself relies on: DNS-1123 names,
non-empty unique containers, parseable resource quantities with
requests <= limits, restart-policy enum, port ranges, workload selectors
actually selecting their templates (the classic misconfiguration the
reference rejects at ValidateReplicaSetSpec)."""

from __future__ import annotations

import re
from typing import Any

from kubernetes_tpu.api.quantity import parse_quantity

# DNS-1123 subdomain (validation.IsDNS1123Subdomain)
_NAME_RE = re.compile(
    r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*$")
_RESTART_POLICIES = ("Always", "OnFailure", "Never")


class ValidationError(ValueError):
    """Invalid API object (HTTP 422 in the reference)."""


def validate(obj: Any) -> None:
    meta = getattr(obj, "metadata", None)
    if meta is None:
        return
    if not meta.name or len(meta.name) > 253 \
            or not _NAME_RE.match(meta.name):
        raise ValidationError(
            f"metadata.name: invalid value {meta.name!r}: must be a "
            f"DNS-1123 subdomain")
    kind = getattr(obj, "kind", "")
    if kind == "Pod":
        _validate_pod(obj)
    elif kind == "Service":
        _validate_service(obj)
    elif kind in ("ReplicaSet", "ReplicationController", "StatefulSet",
                  "Deployment", "Job"):
        _validate_workload(obj)
    elif kind == "PodGroup":
        _validate_podgroup(obj)
    elif kind == "NodeGroup":
        _validate_nodegroup(obj)
    elif kind == "PriorityClass":
        _validate_priorityclass(obj)
    elif kind == "FlowSchema":
        _validate_flowschema(obj)
    elif kind == "PriorityLevelConfiguration":
        _validate_prioritylevel(obj)
    elif kind == "AlertRule":
        _validate_alertrule(obj)
    elif kind == "DeschedulePolicy":
        _validate_deschedulepolicy(obj)


def _validate_quantities(where: str, quantities: dict) -> dict:
    parsed = {}
    for res, qty in quantities.items():
        try:
            parsed[res] = parse_quantity(str(qty))
        except (ValueError, ArithmeticError):
            raise ValidationError(
                f"{where}[{res}]: invalid quantity {qty!r}")
    return parsed


def _validate_pod(pod) -> None:
    if not pod.spec.containers:
        raise ValidationError("spec.containers: must specify at least one")
    seen = set()
    for i, c in enumerate(pod.spec.containers):
        where = f"spec.containers[{i}]"
        if not c.name or not _NAME_RE.match(c.name):
            raise ValidationError(f"{where}.name: invalid value {c.name!r}")
        if c.name in seen:
            raise ValidationError(f"{where}.name: duplicate {c.name!r}")
        seen.add(c.name)
        req = _validate_quantities(f"{where}.resources.requests",
                                   c.requests)
        lim = _validate_quantities(f"{where}.resources.limits", c.limits)
        for res, value in req.items():
            if res in lim and value > lim[res]:
                raise ValidationError(
                    f"{where}.resources.requests[{res}]: must be <= limit "
                    f"({value} > {lim[res]})")
        for p in c.ports:
            for port in (p.container_port, p.host_port):
                if port and not 0 < port <= 65535:
                    raise ValidationError(
                        f"{where}.ports: invalid port {port}")
    if pod.spec.restart_policy not in _RESTART_POLICIES:
        raise ValidationError(
            f"spec.restartPolicy: unsupported value "
            f"{pod.spec.restart_policy!r}")


def _validate_service(svc) -> None:
    for i, p in enumerate(svc.spec.get("ports") or []):
        port = p.get("port")
        if port is None:
            continue
        try:
            number = int(port)
        except (TypeError, ValueError):
            raise ValidationError(
                f"spec.ports[{i}].port: invalid {port!r}")
        if not 0 < number <= 65535:
            raise ValidationError(f"spec.ports[{i}].port: invalid {port}")


def _validate_podgroup(obj) -> None:
    try:
        min_member = obj.min_member
    except (TypeError, ValueError):
        raise ValidationError(
            f"spec.minMember: invalid value {obj.spec.get('minMember')!r}")
    if min_member < 1:
        raise ValidationError("spec.minMember: must be >= 1")
    try:
        timeout = obj.schedule_timeout_seconds
    except (TypeError, ValueError):
        raise ValidationError(
            f"spec.scheduleTimeoutSeconds: invalid value "
            f"{obj.spec.get('scheduleTimeoutSeconds')!r}")
    if timeout <= 0:
        raise ValidationError("spec.scheduleTimeoutSeconds: must be > 0")
    phase = obj.status.get("phase")
    if phase and phase not in type(obj).PHASES:
        raise ValidationError(
            f"status.phase: unsupported value {phase!r}")


def _validate_nodegroup(obj) -> None:
    try:
        min_size, max_size = obj.min_size, obj.max_size
    except (TypeError, ValueError):
        raise ValidationError(
            f"spec.minSize/maxSize: invalid values "
            f"{obj.spec.get('minSize')!r}/{obj.spec.get('maxSize')!r}")
    if min_size < 0:
        raise ValidationError("spec.minSize: must be >= 0")
    if max_size < min_size:
        raise ValidationError(
            f"spec.maxSize: must be >= minSize ({max_size} < {min_size})")


def _validate_deschedulepolicy(obj) -> None:
    try:
        max_moves = obj.max_moves_per_cycle
        obj.priority_cutoff
        cooldown = obj.cooldown_seconds
        rollback = obj.rollback_seconds
    except (TypeError, ValueError):
        raise ValidationError(
            f"spec: invalid DeschedulePolicy values "
            f"{obj.spec.get('maxMovesPerCycle')!r}/"
            f"{obj.spec.get('priorityCutoff')!r}/"
            f"{obj.spec.get('cooldownSeconds')!r}/"
            f"{obj.spec.get('rollbackSeconds')!r}")
    if max_moves < 1:
        raise ValidationError("spec.maxMovesPerCycle: must be >= 1")
    if cooldown < 0:
        raise ValidationError("spec.cooldownSeconds: must be >= 0")
    if rollback <= 0:
        raise ValidationError("spec.rollbackSeconds: must be > 0")


def _validate_priorityclass(obj) -> None:
    try:
        value = int(obj.value)
    except (TypeError, ValueError):
        raise ValidationError(
            f"value: invalid value {obj.value!r}: must be an integer")
    # HighestUserDefinablePriority (pkg/apis/scheduling/types.go): values
    # above one billion are reserved for system classes
    if value > 1_000_000_000:
        raise ValidationError(
            f"value: {value} is greater than the highest user-definable "
            f"priority (1000000000)")


def _validate_flowschema(obj) -> None:
    if not obj.priority_level:
        raise ValidationError("spec.priorityLevel: must name a priority "
                              "level")
    try:
        precedence = obj.matching_precedence
    except (TypeError, ValueError):
        raise ValidationError(
            f"spec.matchingPrecedence: invalid value "
            f"{obj.spec.get('matchingPrecedence')!r}")
    if precedence < 1:
        raise ValidationError("spec.matchingPrecedence: must be >= 1")
    rules = obj.spec.get("rules")
    if rules is not None and not isinstance(rules, list):
        raise ValidationError("spec.rules: must be a list of rule objects")
    for i, rule in enumerate(rules or []):
        if not isinstance(rule, dict):
            raise ValidationError(f"spec.rules[{i}]: must be an object")
        for key in ("users", "groups", "verbs", "resources"):
            val = rule.get(key)
            if val is not None and not isinstance(val, list):
                raise ValidationError(
                    f"spec.rules[{i}].{key}: must be a list")


def _validate_prioritylevel(obj) -> None:
    try:
        shares, queues = obj.shares, obj.queues
        qlen, hand = obj.queue_length_limit, obj.hand_size
    except (TypeError, ValueError):
        raise ValidationError(
            f"spec: invalid queueing configuration {obj.spec!r}")
    if shares < 1:
        raise ValidationError("spec.shares: must be >= 1")
    if queues < 1:
        raise ValidationError("spec.queues: must be >= 1")
    if qlen < 1:
        raise ValidationError("spec.queueLengthLimit: must be >= 1")
    if not 1 <= hand <= queues:
        raise ValidationError(
            f"spec.handSize: must be between 1 and spec.queues "
            f"({hand} vs {queues})")


# alert names render in Events/alert payloads CamelCase, Prometheus-style
_ALERT_NAME_RE = re.compile(r"^[A-Z][a-zA-Z0-9]*$")


def _validate_alertrule(obj) -> None:
    record = obj.spec.get("record", "") or ""
    alert = obj.spec.get("alert", "") or ""
    if bool(record) == bool(alert):
        raise ValidationError(
            "spec: exactly one of spec.record or spec.alert is required")
    if alert and not _ALERT_NAME_RE.match(alert):
        raise ValidationError(
            f"spec.alert: invalid value {alert!r}: must be CamelCase "
            f"([A-Z][a-zA-Z0-9]*)")
    expr = obj.spec.get("expr", "") or ""
    if not expr:
        raise ValidationError("spec.expr: required")
    # the rule engine owns the grammar: reject at admission what the
    # Monitor could never evaluate (lazy import — validation must not
    # drag the monitor in for every other kind)
    from kubernetes_tpu.obs.monitor import QueryError, parse_query
    try:
        parse_query(expr)
    except QueryError as exc:
        raise ValidationError(f"spec.expr: {exc}")
    try:
        for_s = float(obj.spec.get("for", 0) or 0)
    except (TypeError, ValueError):
        raise ValidationError(
            f"spec.for: invalid value {obj.spec.get('for')!r}")
    if for_s < 0:
        raise ValidationError("spec.for: must be >= 0")
    for key in ("labels", "annotations"):
        val = obj.spec.get(key)
        if val is not None and not isinstance(val, dict):
            raise ValidationError(f"spec.{key}: must be a string map")


def _validate_workload(obj) -> None:
    try:
        replicas = obj.replicas
    except (TypeError, ValueError):
        raise ValidationError(
            f"spec.replicas: invalid value "
            f"{obj.spec.get('replicas')!r}")
    if replicas < 0:
        raise ValidationError("spec.replicas: must be non-negative")
    template_labels = ((obj.spec.get("template") or {})
                       .get("metadata") or {}).get("labels") or {}
    selector = obj.spec.get("selector")
    if isinstance(selector, dict) and selector:
        match = selector.get("matchLabels") \
            if "matchLabels" in selector or "matchExpressions" in selector \
            else selector  # RC map selector
        if match and template_labels:
            mismatched = {k: v for k, v in match.items()
                          if template_labels.get(k) != v}
            if mismatched:
                raise ValidationError(
                    f"spec.template.metadata.labels: selector does not "
                    f"match template labels (missing {mismatched})")
