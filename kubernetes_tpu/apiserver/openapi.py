"""OpenAPI (swagger v2) schema serving — the introspection surface.

The reference serves /swagger.json + /openapi/v2 generated from its Go
types (apiserver/pkg/server/routes/openapi.go); kubectl explain reads it
to describe resources field by field (pkg/kubectl/explain). Here the
definitions are derived from the dataclass object model at import time:
dataclass fields map to swagger properties (snake_case -> the wire's
camelCase), nested dataclasses become $ref'd definitions, and docstrings
become descriptions — one source of truth with the codec, no generated
files."""

from __future__ import annotations

import dataclasses
import typing

# tokens that stay upper-case on the wire (hostIP, podCIDR, ...)
_ACRONYMS = {"ip", "cidr", "id", "uid", "tls", "ips"}

# fields whose wire name is not derivable mechanically
_OVERRIDES = {
    "source_component": "source",
}


def wire_name(field_name: str) -> str:
    if field_name in _OVERRIDES:
        return _OVERRIDES[field_name]
    parts = field_name.split("_")
    out = [parts[0]]
    for part in parts[1:]:
        out.append(part.upper() if part in _ACRONYMS
                   else part.capitalize())
    return "".join(out)


_PRIMITIVES = {
    str: {"type": "string"},
    int: {"type": "integer", "format": "int64"},
    float: {"type": "number", "format": "double"},
    bool: {"type": "boolean"},
}


def _type_schema(tp, definitions: dict) -> dict:
    origin = typing.get_origin(tp)
    args = typing.get_args(tp)
    if origin is typing.Union or str(origin) == "types.UnionType":
        non_none = [a for a in args if a is not type(None)]
        if len(non_none) == 1:
            return _type_schema(non_none[0], definitions)
        return {"type": "object"}
    if origin in (list, tuple):
        item = _type_schema(args[0], definitions) if args \
            else {"type": "object"}
        return {"type": "array", "items": item}
    if origin is dict:
        value = _type_schema(args[1], definitions) if len(args) == 2 \
            else {"type": "object"}
        return {"type": "object", "additionalProperties": value}
    if tp in _PRIMITIVES:
        return dict(_PRIMITIVES[tp])
    if dataclasses.is_dataclass(tp):
        return {"$ref": f"#/definitions/{_define(tp, definitions)}"}
    if tp is typing.Any:
        return {"type": "object"}
    return {"type": "object"}


def _define(cls, definitions: dict) -> str:
    name = f"v1.{cls.__name__}"
    if name in definitions:
        return name
    definitions[name] = {}  # cycle guard
    props = {}
    hints = typing.get_type_hints(cls)
    for f in dataclasses.fields(cls):
        schema = _type_schema(hints.get(f.name, str), definitions)
        props[wire_name(f.name)] = schema
    definitions[name] = {
        "description": (cls.__doc__ or "").strip().split("\n\n")[0],
        "type": "object",
        "properties": props,
    }
    return name


def build_swagger() -> dict:
    """The full swagger v2 document (cached by the server)."""
    from kubernetes_tpu.apiserver.http import KIND_TO_CLS, PLURAL_OF

    definitions: dict = {}
    paths: dict = {}
    for kind, cls in sorted(KIND_TO_CLS.items()):
        if not dataclasses.is_dataclass(cls):
            continue
        name = _define(cls, definitions)
        plural = PLURAL_OF.get(kind)
        if plural:
            paths[f"/api/v1/namespaces/{{namespace}}/{plural}"] = {
                "get": {"description": f"list {kind} objects",
                        "responses": {"200": {"schema": {
                            "$ref": f"#/definitions/{name}"}}}}}
    return {
        "swagger": "2.0",
        "info": {"title": "kubernetes-tpu", "version": "v1"},
        "definitions": definitions,
        "paths": paths,
    }


def explain(swagger: dict, kind: str, field_path: list[str]) -> str:
    """Render the kubectl-explain view of `kind` (optionally descending
    into field_path, e.g. ["spec", "containers"])."""
    definitions = swagger.get("definitions") or {}
    name = f"v1.{kind}"
    schema = definitions.get(name)
    if schema is None:
        return f"error: no documentation found for {kind}"

    def resolve(s: dict) -> dict:
        while "$ref" in s:
            s = definitions.get(s["$ref"].split("/")[-1], {})
        if s.get("type") == "array":
            return resolve(s.get("items") or {})
        return s

    trail = [kind]
    for part in field_path:
        props = resolve(schema).get("properties") or {}
        if part not in props:
            return (f"error: field \"{part}\" does not exist in "
                    f"{'.'.join(trail)}")
        schema = props[part]
        trail.append(part)

    resolved = resolve(schema)
    lines = [f"KIND:     {kind}", "VERSION:  v1", ""]
    if len(trail) > 1:
        kind_str = schema.get("type") or "Object"
        if "$ref" in schema:
            kind_str = "Object"
        elif schema.get("type") == "array":
            kind_str = "[]Object" if "$ref" in (schema.get("items") or {}) \
                else f"[]{(schema.get('items') or {}).get('type', 'object')}"
        lines.append(f"FIELD:    {trail[-1]} <{kind_str}>")
        lines.append("")
    desc = resolved.get("description") or "<empty>"
    lines.append("DESCRIPTION:")
    lines.append(f"     {desc}")
    props = resolved.get("properties")
    if props:
        lines.append("")
        lines.append("FIELDS:")
        for prop_name in sorted(props):
            prop = props[prop_name]
            if "$ref" in prop:
                type_str = "Object"
            elif prop.get("type") == "array":
                items = prop.get("items") or {}
                type_str = "[]Object" if "$ref" in items \
                    else f"[]{items.get('type', 'object')}"
            else:
                type_str = prop.get("type", "object")
            lines.append(f"   {prop_name}\t<{type_str}>")
    return "\n".join(lines)
