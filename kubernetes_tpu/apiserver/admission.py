"""Admission control: the mutate-then-validate plugin chain.

The plugin/pkg/admission analog (chain wiring
apiserver/pkg/admission/chain.go; the reference registers 23 plugins —
plugin/pkg/admission/). Implemented plugins are the resource-governance
core plus a defaulting mutator:

- LimitRanger (plugin/pkg/admission/limitranger/admission.go): apply
  per-namespace default container requests/limits from LimitRange objects
  and reject containers exceeding max / under min.
- ResourceQuota (plugin/pkg/admission/resourcequota/admission.go): reject
  pod creation that would push the namespace's aggregate requests.cpu /
  requests.memory / pods count past a ResourceQuota's hard caps; mirrors
  usage into the quota's status.
- DefaultTolerationSeconds
  (plugin/pkg/admission/defaulttolerationseconds): add the 300s
  not-ready/unreachable NoExecute tolerations to pods that don't set them.

The chain hooks the ObjectStore's write path (`ObjectStore(admission=...)`)
— the storage-front position the reference's handler chain occupies; HTTP
maps AdmissionError to 403 Forbidden like quota rejections."""

from __future__ import annotations

import contextlib
import contextvars
from typing import Any

from kubernetes_tpu.api.objects import Toleration
from kubernetes_tpu.api.quantity import parse_quantity


class AdmissionError(Exception):
    """Request rejected by an admission plugin (HTTP 403)."""


# The requesting identity for the current store write. The reference hands
# every plugin an admission.Attributes carrying UserInfo
# (apiserver/pkg/admission/attributes.go); here the HTTP layer sets this
# contextvar around _route so user-aware plugins (NodeRestriction, the
# webhook's AdmissionReview userInfo) see who is writing without threading
# a user parameter through every ObjectStore call site. In-process writes
# (controllers, tests) run with no user — trusted loopback identity.
REQUEST_USER: contextvars.ContextVar = contextvars.ContextVar(
    "ktpu_request_user", default=None)


@contextlib.contextmanager
def request_user(user):
    token = REQUEST_USER.set(user)
    try:
        yield
    finally:
        REQUEST_USER.reset(token)


class AdmissionChain:
    def __init__(self, plugins: list | None = None):
        self.plugins = plugins if plugins is not None else []

    def admit(self, store, obj: Any, operation: str) -> None:
        """Mutating plugins first, then validating — each may mutate `obj`
        in place or raise AdmissionError (chain.go Admit ordering)."""
        user = REQUEST_USER.get()
        for plugin in self.plugins:
            plugin.admit(store, obj, operation, user)


def default_chain() -> AdmissionChain:
    return chain_for("default")


# the reference 1.8 recommended set we implement in-tree; webhook and the
# node/selector restrictors are opt-in by name, like --admission-control
DEFAULT_PLUGINS = ("NamespaceLifecycle", "DefaultTolerationSeconds",
                   "ServiceAccount", "Priority", "LimitRanger",
                   "ResourceQuota")


def chain_for(names: str) -> AdmissionChain:
    """Build a chain from a comma-separated plugin list ('default' = the
    in-tree governance set); unknown names are an error, like the
    reference's --admission-control."""
    registry = {
        "NamespaceLifecycle": NamespaceLifecycle,
        "DefaultTolerationSeconds": DefaultTolerationSeconds,
        "ServiceAccount": ServiceAccountPlugin,
        "LimitRanger": LimitRanger,
        "ResourceQuota": ResourceQuotaPlugin,
        "Priority": PriorityPlugin,
        "NodeRestriction": NodeRestriction,
        "PodNodeSelector": PodNodeSelector,
        "GenericAdmissionWebhook": GenericAdmissionWebhook,
    }
    if names.strip().lower() == "default":
        wanted = list(DEFAULT_PLUGINS)
    else:
        wanted = [n.strip() for n in names.split(",") if n.strip()]
        unknown = [n for n in wanted if n not in registry]
        if unknown:
            raise ValueError(f"unknown admission plugin(s): {unknown}; "
                             f"available: {sorted(registry)}")
    return AdmissionChain([registry[n]() for n in wanted])


# ---------------------------------------------------------------------------


class NamespaceLifecycle:
    """Reject writes into a Terminating (or deleted-while-known) namespace
    (plugin/pkg/admission/namespace/lifecycle). Unlike the reference this
    store is schema-less: a namespace with no Namespace object is treated
    as implicitly Active (auto-provisioned `default` semantics) so
    namespace objects stay opt-in."""

    SKIP_KINDS = frozenset({"Namespace", "CustomResourceDefinition",
                            "Event"})

    def admit(self, store, obj: Any, operation: str,
              user=None) -> None:
        del user
        if operation != "CREATE" or obj.kind in self.SKIP_KINDS:
            return
        ns = obj.metadata.namespace
        try:
            namespace = store.get("Namespace", ns)
        except KeyError:
            return  # implicitly Active
        if namespace.phase == "Terminating" \
                or namespace.metadata.deletion_timestamp is not None:
            raise AdmissionError(
                f"unable to create new content in namespace {ns} because "
                f"it is being terminated")


class ServiceAccountPlugin:
    """plugin/pkg/admission/serviceaccount: default pods'
    spec.serviceAccountName to "default", and reject pods referencing an
    account that does not exist (admission.go DefaultServiceAccountName +
    the MountServiceAccountToken existence check). The "default" account
    itself is auto-managed by the serviceaccounts controller, so its
    momentary absence in a brand-new namespace must not block pods —
    only EXPLICIT references are validated."""

    def admit(self, store, obj: Any, operation: str,
              user=None) -> None:
        del user
        if obj.kind != "Pod" or operation != "CREATE":
            return
        if not obj.spec.service_account_name:
            obj.spec.service_account_name = "default"
        if obj.spec.service_account_name == "default":
            # auto-managed account: its momentary absence in a brand-new
            # namespace must not block pods, explicit or implicit
            return
        try:
            store.get("ServiceAccount", obj.spec.service_account_name,
                      obj.metadata.namespace)
        except KeyError:
            raise AdmissionError(
                f"error looking up service account "
                f"{obj.metadata.namespace}/"
                f"{obj.spec.service_account_name}: not found") from None


NOT_READY_KEY = "node.alpha.kubernetes.io/notReady"
UNREACHABLE_KEY = "node.alpha.kubernetes.io/unreachable"
DEFAULT_TOLERATION_SECONDS = 300


class DefaultTolerationSeconds:
    def admit(self, store, obj: Any, operation: str,
              user=None) -> None:
        del user
        if obj.kind != "Pod" or operation != "CREATE":
            return
        keys = {t.key for t in obj.spec.tolerations}
        for key in (NOT_READY_KEY, UNREACHABLE_KEY):
            if key not in keys:
                obj.spec.tolerations.append(Toleration(
                    key=key, operator="Exists", effect="NoExecute",
                    toleration_seconds=DEFAULT_TOLERATION_SECONDS))


class LimitRanger:
    def admit(self, store, obj: Any, operation: str,
              user=None) -> None:
        del user
        if obj.kind != "Pod" or operation != "CREATE":
            return
        ns = obj.metadata.namespace
        for lr in store.list("LimitRange", namespace=ns,
                             copy_objects=False):
            for item in lr.spec.get("limits", []):
                if item.get("type", "Container") != "Container":
                    continue
                self._apply(obj, item)

    @staticmethod
    def _apply(pod, item: dict) -> None:
        defaults = item.get("default") or {}          # default limits
        default_req = item.get("defaultRequest") or {}
        maxes = item.get("max") or {}
        mins = item.get("min") or {}
        for c in pod.spec.containers:
            for res, qty in default_req.items():
                c.requests.setdefault(res, str(qty))
            for res, qty in defaults.items():
                c.limits.setdefault(res, str(qty))
            for res, cap in maxes.items():
                # both requests and limits must respect max (limitranger
                # maxConstraint applies to each value set)
                for used in (c.requests.get(res), c.limits.get(res)):
                    if used and parse_quantity(used) \
                            > parse_quantity(str(cap)):
                        raise AdmissionError(
                            f"maximum {res} usage per Container is {cap}, "
                            f"but {used} is requested")
            for res, floor in mins.items():
                for used in (c.requests.get(res), c.limits.get(res)):
                    if used and parse_quantity(used) \
                            < parse_quantity(str(floor)):
                        raise AdmissionError(
                            f"minimum {res} usage per Container is {floor}, "
                            f"but {used} is requested")


class ResourceQuotaPlugin:
    TRACKED = ("requests.cpu", "requests.memory", "pods")

    def admit(self, store, obj: Any, operation: str,
              user=None) -> None:
        del user
        if obj.kind != "Pod" or operation != "CREATE":
            return
        ns = obj.metadata.namespace
        quotas = store.list("ResourceQuota", namespace=ns,
                            copy_objects=False)
        if not quotas:
            return
        used = self._namespace_usage(store, ns)
        incoming = self._pod_usage(obj)
        # validate EVERY quota before mutating anything: a later quota's
        # rejection must not leave earlier quotas' status over-counted
        for quota in quotas:
            hard = quota.spec.get("hard") or {}
            for res in self.TRACKED:
                if res not in hard:
                    continue
                total = used.get(res, 0) + incoming.get(res, 0)
                cap = parse_quantity(str(hard[res]))
                if total > cap:
                    raise AdmissionError(
                        f"exceeded quota: {quota.metadata.name}, requested: "
                        f"{res}={incoming.get(res, 0)}, used: "
                        f"{res}={used.get(res, 0)}, limited: "
                        f"{res}={hard[res]}")
        for quota in quotas:
            # mirror usage into status through the store's write path (RV
            # bump + watch event + WAL; the reference's quota controller
            # keeps this fresh asynchronously, admission updates eagerly)
            hard = quota.spec.get("hard") or {}
            status = {
                "hard": dict(hard),
                "used": {res: str(used.get(res, 0) + incoming.get(res, 0))
                         for res in self.TRACKED if res in hard}}
            if quota.status == status:
                continue
            fresh = quota.clone()
            fresh.status = status
            try:
                # CAS against the listed version: a racing mirror write
                # wins and the next admission recomputes from scratch
                store.update(fresh)
            except Exception:  # noqa: BLE001 — usage mirror is best-effort
                pass

    @staticmethod
    def _pod_usage(pod) -> dict:
        out = {"pods": 1, "requests.cpu": 0, "requests.memory": 0}
        for c in pod.spec.containers:
            if "cpu" in c.requests:
                out["requests.cpu"] += parse_quantity(c.requests["cpu"])
            if "memory" in c.requests:
                out["requests.memory"] += parse_quantity(
                    c.requests["memory"])
        return out

    def _namespace_usage(self, store, ns: str) -> dict:
        total = {"pods": 0, "requests.cpu": 0, "requests.memory": 0}
        for pod in store.list("Pod", namespace=ns, copy_objects=False):
            if pod.status.phase in ("Succeeded", "Failed"):
                continue
            usage = self._pod_usage(pod)
            for k, v in usage.items():
                total[k] += v
        return total


class PriorityPlugin:
    """plugin/pkg/admission/priority: resolve spec.priorityClassName to the
    numeric spec.priority at pod CREATE (the scheduler and preemption pass
    only ever read the resolved integer), and keep the PriorityClass
    universe sane — at most one globalDefault class.

    A pod naming an unknown class is rejected; a pod naming no class gets
    the globalDefault class's value if one exists, else priority 0. A pod
    that arrives with a bare spec.priority and no class name keeps it
    (trusted in-process writers — the bench and tests — pre-resolve)."""

    def admit(self, store, obj: Any, operation: str,
              user=None) -> None:
        del user
        if obj.kind == "PriorityClass":
            if operation in ("CREATE", "UPDATE") and obj.global_default:
                for pc in store.list("PriorityClass", copy_objects=False):
                    if pc.global_default \
                            and pc.metadata.name != obj.metadata.name:
                        raise AdmissionError(
                            f"PriorityClass {pc.metadata.name!r} is already "
                            f"marked as globalDefault")
            return
        if obj.kind != "Pod" or operation != "CREATE":
            return
        name = obj.spec.priority_class_name
        if name:
            try:
                pc = store.get("PriorityClass", name)
            except KeyError:
                raise AdmissionError(
                    f"no PriorityClass with name {name!r} was found")
            obj.spec.priority = int(pc.value)
            return
        if obj.spec.priority:
            return
        for pc in store.list("PriorityClass", copy_objects=False):
            if pc.global_default:
                obj.spec.priority_class_name = pc.metadata.name
                obj.spec.priority = int(pc.value)
                return


# ---- user-aware restrictors + the external-webhook seam ----------------


NODES_GROUP = "system:nodes"
NODE_USER_PREFIX = "system:node:"
MIRROR_ANNOTATION = "kubernetes.io/config.mirror"


class NodeRestriction:
    """plugin/pkg/admission/noderestriction/admission.go: limit what a
    NODE identity may write through the API. The NodeAuthorizer scopes
    verbs per object name; this plugin inspects BODIES — without it a
    kubelet could create a pod "bound to itself" that references any
    secret in the namespace and then read that secret through the
    pod-scoped authorizer edge.

    - a node may only create MIRROR pods (the static-pod reflection,
      admission.go:119), and only bound to itself;
    - node-created pods may not reference secrets/configmaps/PVCs
      (admission.go:139-152 — mirror pods must be self-contained);
    - a node may only create/update its OWN Node object.

    Requests with no user (in-process controllers) pass untouched."""

    @staticmethod
    def _node_name(user) -> str | None:
        if user is None or NODES_GROUP not in getattr(user, "groups", ()):
            return None
        name = getattr(user, "name", "")
        if not name.startswith(NODE_USER_PREFIX):
            return None
        return name[len(NODE_USER_PREFIX):]

    def admit(self, store, obj: Any, operation: str,
              user=None) -> None:
        node = self._node_name(user)
        if node is None:
            return
        if obj.kind == "Node":
            if obj.metadata.name != node:
                raise AdmissionError(
                    f"node {node!r} cannot modify node "
                    f"{obj.metadata.name!r}")
            return
        if obj.kind != "Pod":
            return
        if operation == "UPDATE":
            # a node may write pod STATUS only — ANY spec mutation is
            # rejected (admission.go:166 admitPod compares the incoming
            # spec against storage; letting a kubelet grow volume refs or
            # retarget nodeName would reopen the self-grant escalation via
            # the authorizer's pod edge)
            try:
                stored = store.get("Pod", obj.metadata.name,
                                   obj.metadata.namespace)
            except KeyError:
                return
            if obj.spec != stored.spec:
                changed = [f for f in stored.spec.__dataclass_fields__
                           if getattr(obj.spec, f) != getattr(stored.spec, f)]
                raise AdmissionError(
                    f"node {node!r} may only update pod status, not spec "
                    f"({', '.join(changed) or 'spec'})")
            return
        if operation != "CREATE":
            return
        if MIRROR_ANNOTATION not in obj.metadata.annotations:
            raise AdmissionError(
                f"pod does not have {MIRROR_ANNOTATION!r} annotation, "
                f"node {node!r} can only create mirror pods")
        if obj.spec.node_name != node:
            raise AdmissionError(
                f"node {node!r} can only create pods with spec.nodeName "
                f"set to itself")
        for vol in obj.spec.volumes:
            for ref in ("secret", "configMap", "persistentVolumeClaim"):
                if vol.get(ref):
                    raise AdmissionError(
                        f"node {node!r} can not create pods that reference "
                        f"{ref} volumes")


NS_NODE_SELECTOR_ANNOTATION = "scheduler.alpha.kubernetes.io/node-selector"


class PodNodeSelector:
    """plugin/pkg/admission/podnodeselector/admission.go: merge the
    namespace's node-selector annotation into every pod created there;
    a pod whose own selector CONFLICTS with the namespace's is rejected
    (admission.go:103 labels.Conflicts check)."""

    def admit(self, store, obj: Any, operation: str,
              user=None) -> None:
        del user
        if obj.kind != "Pod" or operation != "CREATE":
            return
        try:
            namespace = store.get("Namespace", obj.metadata.namespace)
        except KeyError:
            return
        raw = namespace.metadata.annotations.get(
            NS_NODE_SELECTOR_ANNOTATION, "")
        if not raw:
            return
        ns_selector = {}
        for term in raw.split(","):
            key, _, value = term.strip().partition("=")
            if key:
                ns_selector[key] = value
        for key, value in ns_selector.items():
            if key in obj.spec.node_selector \
                    and obj.spec.node_selector[key] != value:
                raise AdmissionError(
                    f"pod node label selector conflicts with its "
                    f"namespace node label selector on {key!r}")
        obj.spec.node_selector.update(ns_selector)


class WebhookError(AdmissionError):
    """The webhook endpoint failed (failurePolicy=Fail surfaces this)."""


class GenericAdmissionWebhook:
    """plugin/pkg/admission/webhook/admission.go — the external-admission
    seam: every matching hook in each ExternalAdmissionHookConfiguration
    object receives an AdmissionReview and may deny the request; a
    response carrying a JSON patch also mutates it (the mutating-webhook
    shape this vintage was growing toward).

    failurePolicy per hook (admission.go:134): "Ignore" skips an
    unreachable webhook, "Fail" rejects the request.

    CONCURRENCY CAVEAT: the call is a blocking POST issued from inside
    the apiserver's (single-threaded) request path — while a webhook is
    answering, other requests/watches wait, and an endpoint served BY
    this apiserver's own loop would deadlock until the timeout. The
    reference holds the admitting request open the same way but serves
    others concurrently; at this fidelity, keep webhook endpoints
    out-of-process and fast, and keep the timeout short
    (KTPU_WEBHOOK_TIMEOUT_S, default 2s)."""

    TIMEOUT_S = 2.0

    def admit(self, store, obj: Any, operation: str,
              user=None) -> None:
        try:
            configs = store.list("ExternalAdmissionHookConfiguration",
                                 copy_objects=False)
        except Exception:  # noqa: BLE001 — kind not present: no webhooks
            return
        for config in configs:
            # configurations arrive as GenericObjects (schema-less kind):
            # hooks live under body["externalAdmissionHooks"] (the 1.8
            # field) or body["webhooks"] (its successor's name)
            body = getattr(config, "body", None) or {}
            hooks = body.get("externalAdmissionHooks") \
                or body.get("webhooks") or []
            for hook in hooks:
                if not self._matches(hook, obj, operation):
                    continue
                self._call(hook, obj, operation, user)

    @staticmethod
    def _matches(hook: dict, obj: Any, operation: str) -> bool:
        from kubernetes_tpu.apiserver.http import PLURAL_OF

        rules = hook.get("rules") or []
        if not rules:
            return True
        # the served plural, not a naive +"s" (Endpoints -> endpoints,
        # NetworkPolicy -> networkpolicies)
        kind_plural = PLURAL_OF.get(obj.kind, obj.kind.lower() + "s")
        for rule in rules:
            ops = rule.get("operations") or ["*"]
            resources = rule.get("resources") or ["*"]
            if ("*" in ops or operation in ops) and (
                    "*" in resources or kind_plural in resources):
                return True
        return False

    def _call(self, hook: dict, obj: Any, operation: str, user) -> None:
        import base64
        import json as _json
        import urllib.error
        import urllib.request

        import os

        url = (hook.get("clientConfig") or {}).get("url", "")
        policy = hook.get("failurePolicy", "Ignore")
        name = hook.get("name", "<unnamed>")
        timeout = float(os.environ.get("KTPU_WEBHOOK_TIMEOUT_S", 0)
                        or self.TIMEOUT_S)
        review = {
            "kind": "AdmissionReview",
            "spec": {
                "operation": operation,
                "object": obj.to_dict(),
                "kind": obj.kind,
                "namespace": obj.metadata.namespace,
                "userInfo": {
                    "username": getattr(user, "name", ""),
                    "groups": list(getattr(user, "groups", ())),
                },
            },
        }
        try:
            req = urllib.request.Request(
                url, data=_json.dumps(review).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                answer = _json.loads(resp.read())
        except (urllib.error.URLError, OSError, ValueError,
                TimeoutError) as e:
            if policy == "Fail":
                raise WebhookError(
                    f"admission webhook {name!r} failed: {e}") from e
            return  # Ignore: an unreachable webhook fails open
        status = answer.get("status") or {}
        if not status.get("allowed", False):
            message = (status.get("result") or {}).get(
                "message", "denied by external admission webhook")
            raise AdmissionError(
                f"admission webhook {name!r} denied the request: {message}")
        patch_b64 = status.get("patch", "")
        if patch_b64:
            from kubernetes_tpu.apiserver.strategicpatch import json_patch

            patched = json_patch(obj.to_dict(),
                                 _json.loads(base64.b64decode(patch_b64)))
            fresh = type(obj).from_dict(patched)
            obj.__dict__.update(fresh.__dict__)
