"""Admission control: the mutate-then-validate plugin chain.

The plugin/pkg/admission analog (chain wiring
apiserver/pkg/admission/chain.go; the reference registers 23 plugins —
plugin/pkg/admission/). Implemented plugins are the resource-governance
core plus a defaulting mutator:

- LimitRanger (plugin/pkg/admission/limitranger/admission.go): apply
  per-namespace default container requests/limits from LimitRange objects
  and reject containers exceeding max / under min.
- ResourceQuota (plugin/pkg/admission/resourcequota/admission.go): reject
  pod creation that would push the namespace's aggregate requests.cpu /
  requests.memory / pods count past a ResourceQuota's hard caps; mirrors
  usage into the quota's status.
- DefaultTolerationSeconds
  (plugin/pkg/admission/defaulttolerationseconds): add the 300s
  not-ready/unreachable NoExecute tolerations to pods that don't set them.

The chain hooks the ObjectStore's write path (`ObjectStore(admission=...)`)
— the storage-front position the reference's handler chain occupies; HTTP
maps AdmissionError to 403 Forbidden like quota rejections."""

from __future__ import annotations

from typing import Any

from kubernetes_tpu.api.objects import Toleration
from kubernetes_tpu.api.quantity import parse_quantity


class AdmissionError(Exception):
    """Request rejected by an admission plugin (HTTP 403)."""


class AdmissionChain:
    def __init__(self, plugins: list | None = None):
        self.plugins = plugins if plugins is not None else []

    def admit(self, store, obj: Any, operation: str) -> None:
        """Mutating plugins first, then validating — each may mutate `obj`
        in place or raise AdmissionError (chain.go Admit ordering)."""
        for plugin in self.plugins:
            plugin.admit(store, obj, operation)


def default_chain() -> AdmissionChain:
    return chain_for("default")


def chain_for(names: str) -> AdmissionChain:
    """Build a chain from a comma-separated plugin list ('default' = all);
    unknown names are an error, like the reference's --admission-control."""
    registry = {
        "NamespaceLifecycle": NamespaceLifecycle,
        "DefaultTolerationSeconds": DefaultTolerationSeconds,
        "ServiceAccount": ServiceAccountPlugin,
        "LimitRanger": LimitRanger,
        "ResourceQuota": ResourceQuotaPlugin,
    }
    if names.strip().lower() == "default":
        wanted = list(registry)
    else:
        wanted = [n.strip() for n in names.split(",") if n.strip()]
        unknown = [n for n in wanted if n not in registry]
        if unknown:
            raise ValueError(f"unknown admission plugin(s): {unknown}; "
                             f"available: {sorted(registry)}")
    return AdmissionChain([registry[n]() for n in wanted])


# ---------------------------------------------------------------------------


class NamespaceLifecycle:
    """Reject writes into a Terminating (or deleted-while-known) namespace
    (plugin/pkg/admission/namespace/lifecycle). Unlike the reference this
    store is schema-less: a namespace with no Namespace object is treated
    as implicitly Active (auto-provisioned `default` semantics) so
    namespace objects stay opt-in."""

    SKIP_KINDS = frozenset({"Namespace", "CustomResourceDefinition",
                            "Event"})

    def admit(self, store, obj: Any, operation: str) -> None:
        if operation != "CREATE" or obj.kind in self.SKIP_KINDS:
            return
        ns = obj.metadata.namespace
        try:
            namespace = store.get("Namespace", ns)
        except KeyError:
            return  # implicitly Active
        if namespace.phase == "Terminating" \
                or namespace.metadata.deletion_timestamp is not None:
            raise AdmissionError(
                f"unable to create new content in namespace {ns} because "
                f"it is being terminated")


class ServiceAccountPlugin:
    """plugin/pkg/admission/serviceaccount: default pods'
    spec.serviceAccountName to "default", and reject pods referencing an
    account that does not exist (admission.go DefaultServiceAccountName +
    the MountServiceAccountToken existence check). The "default" account
    itself is auto-managed by the serviceaccounts controller, so its
    momentary absence in a brand-new namespace must not block pods —
    only EXPLICIT references are validated."""

    def admit(self, store, obj: Any, operation: str) -> None:
        if obj.kind != "Pod" or operation != "CREATE":
            return
        if not obj.spec.service_account_name:
            obj.spec.service_account_name = "default"
        if obj.spec.service_account_name == "default":
            # auto-managed account: its momentary absence in a brand-new
            # namespace must not block pods, explicit or implicit
            return
        try:
            store.get("ServiceAccount", obj.spec.service_account_name,
                      obj.metadata.namespace)
        except KeyError:
            raise AdmissionError(
                f"error looking up service account "
                f"{obj.metadata.namespace}/"
                f"{obj.spec.service_account_name}: not found") from None


NOT_READY_KEY = "node.alpha.kubernetes.io/notReady"
UNREACHABLE_KEY = "node.alpha.kubernetes.io/unreachable"
DEFAULT_TOLERATION_SECONDS = 300


class DefaultTolerationSeconds:
    def admit(self, store, obj: Any, operation: str) -> None:
        if obj.kind != "Pod" or operation != "CREATE":
            return
        keys = {t.key for t in obj.spec.tolerations}
        for key in (NOT_READY_KEY, UNREACHABLE_KEY):
            if key not in keys:
                obj.spec.tolerations.append(Toleration(
                    key=key, operator="Exists", effect="NoExecute",
                    toleration_seconds=DEFAULT_TOLERATION_SECONDS))


class LimitRanger:
    def admit(self, store, obj: Any, operation: str) -> None:
        if obj.kind != "Pod" or operation != "CREATE":
            return
        ns = obj.metadata.namespace
        for lr in store.list("LimitRange", namespace=ns,
                             copy_objects=False):
            for item in lr.spec.get("limits", []):
                if item.get("type", "Container") != "Container":
                    continue
                self._apply(obj, item)

    @staticmethod
    def _apply(pod, item: dict) -> None:
        defaults = item.get("default") or {}          # default limits
        default_req = item.get("defaultRequest") or {}
        maxes = item.get("max") or {}
        mins = item.get("min") or {}
        for c in pod.spec.containers:
            for res, qty in default_req.items():
                c.requests.setdefault(res, str(qty))
            for res, qty in defaults.items():
                c.limits.setdefault(res, str(qty))
            for res, cap in maxes.items():
                # both requests and limits must respect max (limitranger
                # maxConstraint applies to each value set)
                for used in (c.requests.get(res), c.limits.get(res)):
                    if used and parse_quantity(used) \
                            > parse_quantity(str(cap)):
                        raise AdmissionError(
                            f"maximum {res} usage per Container is {cap}, "
                            f"but {used} is requested")
            for res, floor in mins.items():
                for used in (c.requests.get(res), c.limits.get(res)):
                    if used and parse_quantity(used) \
                            < parse_quantity(str(floor)):
                        raise AdmissionError(
                            f"minimum {res} usage per Container is {floor}, "
                            f"but {used} is requested")


class ResourceQuotaPlugin:
    TRACKED = ("requests.cpu", "requests.memory", "pods")

    def admit(self, store, obj: Any, operation: str) -> None:
        if obj.kind != "Pod" or operation != "CREATE":
            return
        ns = obj.metadata.namespace
        quotas = store.list("ResourceQuota", namespace=ns,
                            copy_objects=False)
        if not quotas:
            return
        used = self._namespace_usage(store, ns)
        incoming = self._pod_usage(obj)
        # validate EVERY quota before mutating anything: a later quota's
        # rejection must not leave earlier quotas' status over-counted
        for quota in quotas:
            hard = quota.spec.get("hard") or {}
            for res in self.TRACKED:
                if res not in hard:
                    continue
                total = used.get(res, 0) + incoming.get(res, 0)
                cap = parse_quantity(str(hard[res]))
                if total > cap:
                    raise AdmissionError(
                        f"exceeded quota: {quota.metadata.name}, requested: "
                        f"{res}={incoming.get(res, 0)}, used: "
                        f"{res}={used.get(res, 0)}, limited: "
                        f"{res}={hard[res]}")
        for quota in quotas:
            # mirror usage into status through the store's write path (RV
            # bump + watch event + WAL; the reference's quota controller
            # keeps this fresh asynchronously, admission updates eagerly)
            hard = quota.spec.get("hard") or {}
            status = {
                "hard": dict(hard),
                "used": {res: str(used.get(res, 0) + incoming.get(res, 0))
                         for res in self.TRACKED if res in hard}}
            if quota.status == status:
                continue
            fresh = quota.clone()
            fresh.status = status
            try:
                store.update(fresh, check_version=False)
            except Exception:  # noqa: BLE001 — usage mirror is best-effort
                pass

    @staticmethod
    def _pod_usage(pod) -> dict:
        out = {"pods": 1, "requests.cpu": 0, "requests.memory": 0}
        for c in pod.spec.containers:
            if "cpu" in c.requests:
                out["requests.cpu"] += parse_quantity(c.requests["cpu"])
            if "memory" in c.requests:
                out["requests.memory"] += parse_quantity(
                    c.requests["memory"])
        return out

    def _namespace_usage(self, store, ns: str) -> dict:
        total = {"pods": 0, "requests.cpu": 0, "requests.memory": 0}
        for pod in store.list("Pod", namespace=ns, copy_objects=False):
            if pod.status.phase in ("Succeeded", "Failed"):
                continue
            usage = self._pod_usage(pod)
            for k, v in usage.items():
                total[k] += v
        return total
