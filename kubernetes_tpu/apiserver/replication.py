"""Replicated store: WAL-streamed hot standbys with fenced failover.

The HA plane so far (PRs 12-14) made the *apiservers* stateless replicas
— every one of them sat over ONE ObjectStore with one WAL: the last
single point of failure. This module replicates the store itself, the
in-process analog of etcd's raft log shipping
(apiserver/pkg/storage/etcd3/store.go over mvcc/wal):

- the **primary** streams every published WatchEvent as a WAL-shaped
  record (the exact `{"op","rv","kind","ns","name","obj"}` line the
  store's own log uses, plus the fencing epoch) to N **standbys** over
  the existing TCP transport, via an `event_taps` hook — O(events), in
  rv order, encoded once per event;
- a new or lagging follower catches up from a **snapshot** first, in the
  compaction framing (PR 7): one `SNAP{rv}` header, `OBJ` lines, an
  `END{count}` trailer. A snapshot whose trailer never arrives (primary
  died mid-catch-up) is DISCARDED wholesale and re-requested — a standby
  never serves from a torn snapshot;
- failover is **fenced**: a monotonically increasing epoch token is
  minted at promotion (a CAS on the same Endpoints lock object the
  `LeaderElector` lease rides), stamped on every replicated record and
  checked on every write — a deposed primary returning from a GC pause
  or partition gets `FencedWrite` instead of split-braining the fleet,
  and the rejection carries the new primary's endpoint so clients chase;
- promotion rides the existing `client/leaderelection.py` machinery: the
  standby that wins the lease replays/verifies its own durable WAL
  prefix (every applied record was re-logged locally), bumps the epoch,
  installs the streaming tap, and advertises.

All replicas share one resourceVersion sequence, so `watch(since=rv)` —
and therefore `FailoverWatch`'s gapless `since=last_rv` resume — works
unchanged against any replica.
"""

from __future__ import annotations

import asyncio
import json
import logging
import random
import time
from typing import Any, Callable

from kubernetes_tpu.api.objects import Endpoints, ObjectMeta
from kubernetes_tpu.apiserver.store import (
    FencedWrite,
    NotFound,
    ObjectStore,
    WatchEvent,
)
from kubernetes_tpu.client.leaderelection import LeaderElector

log = logging.getLogger(__name__)

# the promotion lock: LeaderElector lease record AND fencing-epoch ledger
# live in the annotations of this one Endpoints object, so lease and
# epoch move under the same CAS discipline
REPLICATION_LOCK = "ktpu-store-primary"
REPLICATION_LOCK_NS = "kube-system"
EPOCH_ANNOTATION = "ktpu.io/fencing-epoch"
ENDPOINT_ANNOTATION = "ktpu.io/primary-endpoint"
REP_ENDPOINT_ANNOTATION = "ktpu.io/replication-endpoint"

_mx = None


def _metrics():
    global _mx
    if _mx is None:
        from kubernetes_tpu.obs import REGISTRY

        _mx = {
            "records": REGISTRY.counter(
                "store_replication_records_total",
                "Replicated WAL records by outcome (streamed at the "
                "primary, applied/rejected at a standby).",
                labels=("result",)),
            "snapshots": REGISTRY.counter(
                "store_replication_snapshots_total",
                "Catch-up snapshots by outcome (sent, applied, or "
                "discarded because the END trailer never arrived).",
                labels=("result",)),
            "fenced": REGISTRY.counter(
                "store_replication_fenced_writes_total",
                "Writes rejected by the fencing guard (standby or "
                "deposed-primary write attempts)."),
            "promotions": REGISTRY.counter(
                "store_replication_promotions_total",
                "Standby-to-primary promotions (epoch mints)."),
            "promotion_seconds": REGISTRY.histogram(
                "store_replication_promotion_seconds",
                "Primary-outage to promoted-and-serving latency.",
                buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)),
            "epoch": REGISTRY.gauge(
                "store_replication_epoch",
                "This process's highest observed fencing epoch."),
            "followers": REGISTRY.gauge(
                "store_replication_followers",
                "Standby connections currently streamed by the primary."),
        }
    return _mx


# ---------------------------------------------------------------------------
# fencing ledger


class FencingLedger:
    """The fencing-token authority, backed by the coordination store (the
    quorum the lease also lives in — in-process here, etcd's role in the
    reference). `mint` is a CAS (`guaranteed_update`) on the lock
    object's annotations, so epochs are strictly monotonic even under
    racing promotions; `current` is the read every fencing check and
    every follower re-resolve performs."""

    def __init__(self, store, lock_name: str = REPLICATION_LOCK,
                 lock_namespace: str = REPLICATION_LOCK_NS):
        self.store = store
        self.lock_name = lock_name
        self.lock_namespace = lock_namespace

    def current(self) -> tuple[int, str, str]:
        """-> (epoch, primary apiserver endpoint, replication endpoint).
        (0, "", "") before the first promotion. Raises ConnectionError
        (et al.) when the quorum is unreachable — callers decide whether
        that is fail-safe-reject (the write guard) or retry (a follower)."""
        try:
            obj = self.store.get("Endpoints", self.lock_name,
                                 self.lock_namespace)
        except NotFound:
            return 0, "", ""
        ann = obj.metadata.annotations or {}
        return (int(ann.get(EPOCH_ANNOTATION, 0) or 0),
                ann.get(ENDPOINT_ANNOTATION, ""),
                ann.get(REP_ENDPOINT_ANNOTATION, ""))

    def mint(self, endpoint: str, rep_endpoint: str) -> int:
        """Bump the epoch and advertise `endpoint` as the new primary.
        Returns the minted epoch."""
        minted = 0

        def bump(obj):
            nonlocal minted
            if obj.metadata.annotations is None:
                obj.metadata.annotations = {}
            ann = obj.metadata.annotations
            minted = int(ann.get(EPOCH_ANNOTATION, 0) or 0) + 1
            ann[EPOCH_ANNOTATION] = str(minted)
            ann[ENDPOINT_ANNOTATION] = endpoint
            ann[REP_ENDPOINT_ANNOTATION] = rep_endpoint
            return obj

        try:
            self.store.guaranteed_update("Endpoints", self.lock_name,
                                         self.lock_namespace, bump)
        except NotFound:
            # promotion before any election wrote the lock object (the
            # bootstrap primary): create it carrying epoch 1
            minted = 1
            self.store.create(Endpoints(metadata=ObjectMeta(
                name=self.lock_name, namespace=self.lock_namespace,
                annotations={EPOCH_ANNOTATION: "1",
                             ENDPOINT_ANNOTATION: endpoint,
                             REP_ENDPOINT_ANNOTATION: rep_endpoint})))
        return minted

    def check(self, epoch: int) -> tuple[bool, int, str]:
        """Fencing check for one write: does `epoch` still rule?
        -> (ok, current epoch, current primary endpoint)."""
        cur, endpoint, _rep = self.current()
        return cur == epoch, cur, endpoint


class CoordinationGate:
    """A replica's view of the coordination store. Severing the gate
    simulates a partition from the quorum: every verb raises
    ConnectionError, which the elector counts as a failed attempt
    (`_Unavailable`) and the fencing guard counts as cannot-verify —
    fail-safe reject, never fail-open."""

    def __init__(self, inner):
        self._inner = inner
        self.severed = False

    def __getattr__(self, name: str):
        attr = getattr(self._inner, name)
        if not callable(attr):
            if self.severed:
                raise ConnectionError("partitioned from coordination quorum")
            return attr

        def call(*args, **kwargs):
            if self.severed:
                raise ConnectionError("partitioned from coordination quorum")
            return attr(*args, **kwargs)

        return call


# ---------------------------------------------------------------------------
# record framing (the WAL line, plus epoch + event type)


def encode_record(event: WatchEvent, epoch: int) -> dict:
    obj = event.obj
    rec = {
        "op": "DELETE" if event.type == "DELETED" else "PUT",
        "type": event.type,
        "rv": event.resource_version,
        "kind": event.kind,
        "ns": obj.metadata.namespace or "default",
        "name": obj.metadata.name,
        "epoch": epoch,
        # included for DELETE too: the standby re-logs the record to its
        # own WAL and fans the full object out to its local watchers
        "obj": obj.to_dict(),
    }
    return rec


def decode_record(rec: dict) -> WatchEvent:
    from kubernetes_tpu.apiserver.http import decode_object

    obj = decode_object(rec["kind"], rec["obj"])
    rv = int(rec["rv"])
    obj.metadata.resource_version = str(rv)
    ev_type = rec.get("type") or (
        "DELETED" if rec["op"] == "DELETE" else "MODIFIED")
    return WatchEvent(ev_type, rec["kind"], obj, rv)


# ---------------------------------------------------------------------------
# the replicated store


class ReplicatedStore(ObjectStore):
    """An ObjectStore that participates in primary/standby replication.

    Every mutating verb runs the fencing check first: a standby — or a
    primary whose epoch token has been superseded, or one that cannot
    reach the coordination quorum to verify it — raises `FencedWrite`
    BEFORE any state is touched, so no resourceVersion is ever allocated
    under a stale epoch. Reads and watches serve from any role (one
    shared rv sequence; standbys may trail by in-flight records)."""

    def __init__(self, *args, replica: str = "", **kwargs):
        super().__init__(*args, **kwargs)
        self.replica = replica
        self.role = "standby"
        self.epoch = 0                      # highest epoch this replica saw
        self.known_primary: tuple[int, str] = (0, "")
        # wired by StoreReplica: () -> (ok, cur_epoch, cur_endpoint);
        # must not raise (quorum-unreachable returns ok=False)
        self.verify_lease: Callable[[], tuple[bool, int, str]] | None = None
        # wired by StoreReplica: (newer_epoch, endpoint) -> None, called
        # synchronously inside a rejected write when the guard OBSERVES
        # the newer epoch — schedules demote+rejoin, must not raise
        self.on_deposed: Callable[[int, str], None] | None = None
        self.fenced_writes = 0
        self.replicated_applied = 0
        # the epoch the LAST applied/published record was stamped with —
        # advertised in HELLO so a primary can detect a dead-timeline
        # suffix (records applied under an older epoch, beyond the rv the
        # new timeline diverged at) and force a snapshot reset instead of
        # tail-feeding an aliased rv range
        self.applied_epoch = 0

    # ---- fencing guard ----

    def _fence_check(self) -> None:
        if self.role == "primary":
            if self.verify_lease is None:
                return  # unmanaged store (unit tests drive roles directly)
            ok, cur, endpoint = self.verify_lease()
            if ok:
                return
            self.fenced_writes += 1
            _metrics()["fenced"].inc()
            if cur > self.epoch and self.on_deposed is not None:
                self.on_deposed(cur, endpoint)
            raise FencedWrite(
                f"write fenced: replica {self.replica} holds epoch "
                f"{self.epoch} but the ledger says {cur or 'unreachable'}",
                epoch=cur, endpoint=endpoint)
        epoch, endpoint = self.known_primary
        self.fenced_writes += 1
        _metrics()["fenced"].inc()
        raise FencedWrite(
            f"replica {self.replica} is a standby (primary epoch {epoch} "
            f"at {endpoint or 'unknown'})", epoch=epoch, endpoint=endpoint)

    def create(self, obj: Any, *, copy: bool = True) -> Any:
        self._fence_check()
        return super().create(obj, copy=copy)

    def create_many(self, objs: list[Any]) -> list[Any]:
        self._fence_check()
        return super().create_many(objs)

    def update(self, obj: Any, *, check_version: bool = True) -> Any:
        self._fence_check()
        return super().update(obj, check_version=check_version)

    def delete(self, kind: str, name: str, namespace: str = "default") -> Any:
        self._fence_check()
        return super().delete(kind, name, namespace)

    def bind(self, binding) -> Any:
        self._fence_check()
        return super().bind(binding)

    def bind_many(self, bindings) -> tuple[list, list]:
        self._fence_check()
        return super().bind_many(bindings)

    # ---- standby apply ----

    def apply_replicated(self, event: WatchEvent, epoch: int = 0) -> None:
        """Apply one replicated record on a standby: everything
        `apply_external_event` does (bucket, rv clock, history, local
        watcher fan-out) PLUS re-logging the record to this replica's OWN
        WAL — the durable prefix a promoted standby vouches for."""
        if self._wal is not None:
            self._append_wal(event)
        self.apply_external_event(event)
        self.replicated_applied += 1
        if epoch > self.applied_epoch:
            self.applied_epoch = epoch

    def reset_from_snapshot(self, objs: list[tuple[str, str, str, int, Any]],
                            snap_rv: int, snap_epoch: int = 0) -> None:
        """Install a validated catch-up snapshot wholesale: local state
        (possibly a diverged or empty prefix) is discarded and replaced —
        the pg_rewind analog. Local watchers are evicted (they relist);
        the durable snapshot+WAL are rewritten to match via compact()."""
        for watcher in list(self._watchers):
            self._evict_watcher(watcher)
        self._objects.clear()
        self._history.clear()
        self._cluster_ip_counter = 0
        self._rv = snap_rv
        for kind, ns, name, rv, obj in objs:
            self._bucket(kind)[(ns, name)] = obj
            if kind == "Service":
                self._reserve_cluster_ip(obj.spec.get("clusterIP", ""))
            self._rv = max(self._rv, rv)
        if snap_epoch > self.applied_epoch:
            self.applied_epoch = snap_epoch
        if self._persist_path:
            self.compact()

    def replay_prefix(self) -> int:
        """Promotion-time WAL replay: re-read this replica's own log and
        verify the durable prefix against the in-memory clock (a crash-
        restarted replica replays for real in __init__; the live path
        re-reads to confirm nothing the primary streamed was lost before
        the fsync barrier). Returns the verified record count."""
        import os

        if not self._persist_path:
            return 0
        if self._wal is not None:
            self._wal.flush()
            os.fsync(self._wal.fileno())
        count = 0
        max_rv = 0
        if os.path.exists(self._persist_path):
            with open(self._persist_path, encoding="utf-8",
                      errors="replace") as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                        max_rv = max(max_rv, int(rec.get("rv", 0)))
                    except (ValueError, TypeError):
                        continue
                    count += 1
        if max_rv > self._rv:
            log.warning("%s: WAL prefix runs ahead of memory "
                        "(rv %d > %d) — replay was incomplete",
                        self.replica, max_rv, self._rv)
        return count


# ---------------------------------------------------------------------------
# one replica's runtime: apiserver + replication stream + election


class StoreReplica:
    """One store replica: a `ReplicatedStore`, the APIServer over it, a
    replication listener (streams the WAL to followers while primary), a
    follower loop (chases the ledger's primary while standby), and a
    `LeaderElector` candidacy whose win is the promotion path.

    All async pieces run on the loop `start()` is awaited on (the
    testing harness puts a whole replica set on one background loop)."""

    def __init__(self, index: int, coord_store, *,
                 host: str = "127.0.0.1",
                 persist_path: str | None = None,
                 watch_window: int = 4096,
                 lock_name: str = REPLICATION_LOCK,
                 lock_namespace: str = REPLICATION_LOCK_NS,
                 lease_duration: float = 1.0,
                 renew_deadline: float = 0.7,
                 retry_period: float = 0.05,
                 follower_queue: int = 8192,
                 server_kwargs: dict | None = None):
        self.index = index
        self.identity = f"store-{index}"
        self.host = host
        self.coord = CoordinationGate(coord_store)
        self.ledger = FencingLedger(self.coord, lock_name, lock_namespace)
        self.lock_name = lock_name
        self.lock_namespace = lock_namespace
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self.follower_queue = follower_queue
        self.server_kwargs = dict(server_kwargs or {})
        self.store = ReplicatedStore(watch_window=watch_window,
                                     persist_path=persist_path,
                                     replica=self.identity)
        self.store.verify_lease = self._verify_lease
        self.store.on_deposed = self._deposed_from_guard
        self.api = None                      # APIServer, built in start()
        self.api_port = 0
        self.rep_port = 0
        self._rep_server = None
        self._followers: dict[int, asyncio.Queue] = {}
        self._follower_writers: dict[int, asyncio.StreamWriter] = {}
        self._next_follower_id = 0
        self._follow_task: asyncio.Task | None = None
        self._follow_writer: asyncio.StreamWriter | None = None
        self._elector_task: asyncio.Task | None = None
        self._elector: LeaderElector | None = None
        self._stopped = False
        self.killed = False
        self.partitioned = False
        self.promoted_at = 0.0
        # the rv this replica's timeline began ruling at: everything at or
        # below it is the shared prefix every in-sync follower also holds
        # (the old primary streamed in rv order from one source); anything
        # ABOVE it applied under an older epoch is a dead-timeline suffix
        self.promo_rv = 0
        self.on_promoted: Callable[["StoreReplica"], None] | None = None
        # drill knob: while primary, abort the follower connection after
        # streaming this many snapshot OBJ lines (one-shot) — drives the
        # torn-mid-catch-up coverage without killing the whole process
        self.snapshot_fault_after = 0
        # observability (per-replica mirrors of the registry families)
        self.records_sent = 0
        self.snapshots_sent = 0
        self.snapshots_discarded = 0
        self.catchups = 0

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.api_port}"

    @property
    def rep_endpoint(self) -> str:
        return f"{self.host}:{self.rep_port}"

    # ---- lifecycle ----

    async def start(self, *, start_election: bool = True) -> None:
        from kubernetes_tpu.apiserver.http import APIServer

        self.api = APIServer(self.store, host=self.host, port=self.api_port,
                             replica_id=self.identity, **self.server_kwargs)
        await self.api.start()
        self.api_port = self.api.port
        self._rep_server = await asyncio.start_server(
            self._serve_follower, self.host, self.rep_port)
        self.rep_port = self._rep_server.sockets[0].getsockname()[1]
        self.killed = False
        if start_election:
            self.start_election()

    def start_election(self) -> None:
        loop = asyncio.get_running_loop()
        if self._follow_task is None or self._follow_task.done():
            self._follow_task = loop.create_task(self._follow())
        if self._elector_task is None or self._elector_task.done():
            self._elector_task = loop.create_task(self._run_elector())

    def kill(self) -> None:
        """SIGKILL equivalent: apiserver, replication stream, and
        candidacy all vanish — but local state AND beliefs (role, epoch)
        freeze exactly as they were, so a later `resurrect()` models the
        GC-pause return of a primary that never learned it was deposed."""
        self.killed = True
        for task in (self._elector_task, self._follow_task):
            if task is not None:
                task.cancel()
        self._elector_task = self._follow_task = None
        if self._elector is not None:
            self._elector.stop()
            self._elector = None
        self._drop_followers()
        if self._rep_server is not None:
            self._rep_server.close()
            self._rep_server = None
        if self._follow_writer is not None:
            try:
                self._follow_writer.close()
            except Exception:  # noqa: BLE001 — already torn down
                pass
            self._follow_writer = None
        if self.api is not None:
            self.api.kill()

    async def resurrect(self) -> None:
        """Bring a killed replica back believing whatever it believed:
        the apiserver rebinds its old port over the SAME store, but the
        candidacy does NOT restart — a resurrected stale primary must
        learn of its deposition the hard way (first fenced write or
        follower NACK), at which point `_deposed_from_guard` demotes it
        and it rejoins as a standby. A replica that was a standby when
        killed rejoins the quorum immediately."""
        from kubernetes_tpu.apiserver.http import APIServer

        self.api = APIServer(self.store, host=self.host, port=self.api_port,
                             replica_id=self.identity, **self.server_kwargs)
        await self.api.start()
        self._rep_server = await asyncio.start_server(
            self._serve_follower, self.host, self.rep_port)
        self.killed = False
        if self.store.role != "primary":
            self.start_election()

    def partition(self) -> None:
        """Sever this replica from coordination quorum AND peers: lease
        reads/renews fail (the elector loses leadership after
        renew_deadline; the write guard fail-safe rejects immediately),
        follower links drop both ways."""
        self.partitioned = True
        self.coord.severed = True
        self._drop_followers()
        if self._follow_writer is not None:
            try:
                self._follow_writer.close()
            except Exception:  # noqa: BLE001 — already torn down
                pass
            self._follow_writer = None

    def heal(self) -> None:
        self.partitioned = False
        self.coord.severed = False

    async def stop(self) -> None:
        self._stopped = True
        self.kill()
        if self.api is not None:
            self.api.kill()

    # ---- fencing plumbing ----

    def _verify_lease(self) -> tuple[bool, int, str]:
        try:
            ok, cur, endpoint = self.ledger.check(self.store.epoch)
        except Exception:  # noqa: BLE001 — quorum unreachable: fail safe
            return False, 0, ""
        return ok, cur, endpoint

    def _deposed_from_guard(self, epoch: int, endpoint: str) -> None:
        """A write (or a follower HELLO) just proved a newer epoch rules.
        Demote synchronously — the very next write must see standby role —
        and schedule the rejoin (follow + candidacy) onto the loop."""
        log.warning("%s: deposed — epoch %d at %s supersedes %d",
                    self.identity, epoch, endpoint, self.store.epoch)
        self.store.role = "standby"
        self.store.epoch = epoch
        self.store.known_primary = (epoch, endpoint)
        _metrics()["epoch"].set(epoch)
        self._drop_followers()
        if not self.killed and not self._stopped:
            try:
                asyncio.get_running_loop().call_soon(self.start_election)
            except RuntimeError:  # no loop: direct-driven unit test
                pass

    # ---- election / promotion ----

    async def _run_elector(self) -> None:
        rng = random.Random(f"ktpu-store-elector-{self.index}")
        while not self._stopped and not self.killed:
            elector = LeaderElector(
                self.coord, self.identity,
                lock_name=self.lock_name, lock_namespace=self.lock_namespace,
                lease_duration=self.lease_duration,
                renew_deadline=self.renew_deadline,
                retry_period=self.retry_period,
                on_started_leading=self._lead, rng=rng)
            self._elector = elector
            try:
                await elector.run()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — candidacy survives hiccups
                log.exception("%s: elector crashed; recontending",
                              self.identity)
            if self.store.role == "primary":
                # lease lost (partition, renew deadline): step down before
                # anyone else can mint — CP behavior, never two writers
                self._demote("lease lost")
            await asyncio.sleep(self.retry_period)

    async def _lead(self) -> None:
        await self._promote()
        # hold leadership while primary: returning stops the elector's
        # renew loop (it treats finished work as done leading)
        while not self._stopped and self.store.role == "primary":
            await asyncio.sleep(self.retry_period)

    async def _promote(self) -> None:
        """The standby-to-primary transition: stop following, replay the
        durable WAL prefix, mint the next epoch (the CAS also advertises
        our endpoints), flip the role, start streaming."""
        if self._follow_writer is not None:
            try:
                self._follow_writer.close()
            except Exception:  # noqa: BLE001 — already torn down
                pass
            self._follow_writer = None
        replayed = self.store.replay_prefix()
        try:
            epoch = self.ledger.mint(self.endpoint, self.rep_endpoint)
        except Exception:  # noqa: BLE001 — quorum gone mid-promotion:
            # surrender leadership, the elector loop recontends
            log.warning("%s: epoch mint failed; abandoning promotion",
                        self.identity)
            self.store.role = "standby"
            return
        self.store.epoch = epoch
        self.store.known_primary = (epoch, self.endpoint)
        self.store.role = "primary"
        self.promo_rv = self.store._rv
        self.store.applied_epoch = epoch
        self._install_tap()
        m = _metrics()
        m["promotions"].inc()
        m["epoch"].set(epoch)
        self.promoted_at = time.monotonic()
        log.info("%s: promoted to primary, epoch %d (%d WAL records "
                 "verified)", self.identity, epoch, replayed)
        try:
            self.api.advertise()
        except Exception:  # noqa: BLE001 — discovery is best-effort; the
            # fenced-response chase finds the primary without it
            pass
        if self.on_promoted is not None:
            self.on_promoted(self)

    def _demote(self, why: str) -> None:
        log.warning("%s: demoted (%s)", self.identity, why)
        self.store.role = "standby"
        self._drop_followers()

    # ---- primary side: the streaming tap + follower serving ----

    def _install_tap(self) -> None:
        if self._tap not in self.store.event_taps:
            self.store.event_taps.append(self._tap)

    def _tap(self, event: WatchEvent) -> None:
        """Synchronous event tap on the primary store: encode once, fan
        out to every follower queue. Never raises; a follower that cannot
        keep up is dropped (it reconnects and snapshot-catches-up)."""
        if self.store.role != "primary" or not self._followers:
            return
        try:
            line = json.dumps(encode_record(event, self.store.epoch)) + "\n"
        except Exception:  # noqa: BLE001 — taps must never raise
            return
        item = (event.resource_version, line)
        for fid in list(self._followers):
            try:
                self._followers[fid].put_nowait(item)
            except asyncio.QueueFull:
                self._drop_follower(fid)
            except KeyError:
                pass
        self.records_sent += 1
        _metrics()["records"].labels("streamed").inc()

    def _drop_follower(self, fid: int) -> None:
        self._followers.pop(fid, None)
        writer = self._follower_writers.pop(fid, None)
        if writer is not None:
            try:
                writer.close()
            except Exception:  # noqa: BLE001 — already torn down
                pass
        _metrics()["followers"].set(len(self._followers))

    def _drop_followers(self) -> None:
        for fid in list(self._followers):
            self._drop_follower(fid)

    async def _serve_follower(self, reader: asyncio.StreamReader,
                              writer: asyncio.StreamWriter) -> None:
        fid = None
        try:
            raw = await reader.readline()
            if not raw:
                return
            hello = json.loads(raw)
            hello_epoch = int(hello.get("epoch", 0) or 0)
            if self.partitioned or self.store.role != "primary":
                epoch, endpoint = self.store.known_primary
                writer.write(json.dumps({
                    "op": "NACK", "epoch": epoch,
                    "endpoint": endpoint}).encode() + b"\n")
                await writer.drain()
                return
            if hello_epoch > self.store.epoch:
                # the follower has seen a future epoch: WE are the stale
                # primary returning from a pause — fence ourselves now
                writer.write(json.dumps({
                    "op": "NACK", "epoch": hello_epoch,
                    "endpoint": ""}).encode() + b"\n")
                await writer.drain()
                self._deposed_from_guard(hello_epoch, "")
                return
            # register the live queue BEFORE the catch-up so nothing
            # published during it can slip between tail and stream
            queue: asyncio.Queue = asyncio.Queue(self.follower_queue)
            fid = self._next_follower_id
            self._next_follower_id += 1
            self._followers[fid] = queue
            self._follower_writers[fid] = writer
            _metrics()["followers"].set(len(self._followers))
            writer.write(json.dumps({
                "op": "EPOCH", "epoch": self.store.epoch,
                "endpoint": self.endpoint}).encode() + b"\n")
            sent_rv = await self._send_catchup(
                writer, int(hello.get("have_rv", 0) or 0),
                int(hello.get("applied_epoch", 0) or 0))
            await writer.drain()
            while not self._stopped:
                rv, line = await queue.get()
                if rv <= sent_rv:
                    continue  # the catch-up already carried this record
                writer.write(line.encode())
                await writer.drain()
        except (asyncio.CancelledError, GeneratorExit):
            raise
        except Exception:  # noqa: BLE001 — a follower connection dying is
            # routine; it reconnects and re-requests
            pass
        finally:
            if fid is not None:
                self._drop_follower(fid)
            try:
                writer.close()
            except Exception:  # noqa: BLE001 — already torn down
                pass

    async def _send_catchup(self, writer: asyncio.StreamWriter,
                            have_rv: int, applied_epoch: int = 0) -> int:
        """History tail when the follower's rv is still inside the ring
        buffer AND its prefix is provably shared; full SNAP/OBJ/END
        snapshot otherwise (fresh follower, lagging follower, one whose
        prefix ran AHEAD of ours, or — the subtle case — a DIVERGED one:
        its last records were applied under an older epoch at rvs beyond
        our promotion point. The dead primary may have published records
        that never reached the promoted standby; the new timeline reuses
        those rv numbers for different content, so an rv-range check
        alone would silently merge the two timelines. Such a follower is
        reset wholesale, the pg_rewind move). Returns the rv the catch-up
        covers through."""
        st = self.store
        oldest = (st._history[0].resource_version
                  if st._history else st._rv + 1)
        diverged = (applied_epoch and applied_epoch < st.epoch
                    and have_rv > self.promo_rv)
        if not diverged and oldest - 1 <= have_rv <= st._rv:
            tail = [e for e in st._history if e.resource_version > have_rv]
            for ev in tail:
                writer.write((json.dumps(
                    encode_record(ev, st.epoch)) + "\n").encode())
            if tail:
                self.records_sent += len(tail)
                _metrics()["records"].labels("streamed").inc(len(tail))
            return st._rv
        snap_rv = st._rv
        writer.write(json.dumps(
            {"op": "SNAP", "rv": snap_rv,
             "epoch": st.epoch}).encode() + b"\n")
        count = 0
        for kind, bucket in st._objects.items():
            for (ns, name), obj in list(bucket.items()):
                writer.write((json.dumps({
                    "op": "OBJ", "kind": kind, "ns": ns, "name": name,
                    "rv": int(obj.metadata.resource_version or 0),
                    "obj": obj.to_dict()}) + "\n").encode())
                count += 1
                if count % 256 == 0:
                    await writer.drain()
                if self.snapshot_fault_after \
                        and count >= self.snapshot_fault_after:
                    # drill knob: die mid-catch-up, END never sent — the
                    # follower must discard everything it buffered
                    self.snapshot_fault_after = 0
                    await writer.drain()
                    raise ConnectionError("injected mid-snapshot fault")
        writer.write(json.dumps(
            {"op": "END", "count": count}).encode() + b"\n")
        self.snapshots_sent += 1
        _metrics()["snapshots"].labels("sent").inc()
        return snap_rv

    # ---- standby side: follow the ledger's primary ----

    async def _follow(self) -> None:
        st = self.store
        while not self._stopped and not self.killed:
            if st.role == "primary":
                return
            if self.partitioned:
                await asyncio.sleep(self.retry_period)
                continue
            try:
                epoch, endpoint, rep_endpoint = self.ledger.current()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — quorum hiccup: retry
                await asyncio.sleep(self.retry_period)
                continue
            if epoch > st.epoch:
                st.epoch = epoch
                _metrics()["epoch"].set(epoch)
            if epoch:
                st.known_primary = (epoch, endpoint)
            if not rep_endpoint or rep_endpoint == self.rep_endpoint:
                await asyncio.sleep(self.retry_period)
                continue
            host, _, port = rep_endpoint.rpartition(":")
            try:
                reader, writer = await asyncio.open_connection(
                    host, int(port))
                self._follow_writer = writer
                writer.write(json.dumps({
                    "op": "HELLO", "have_rv": st._rv, "epoch": st.epoch,
                    "applied_epoch": st.applied_epoch,
                    "replica": self.identity}).encode() + b"\n")
                await writer.drain()
                await self._consume(reader)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — primary gone mid-stream:
                # re-resolve from the ledger and reconnect
                pass
            finally:
                if self._follow_writer is not None:
                    try:
                        self._follow_writer.close()
                    except Exception:  # noqa: BLE001 — already torn down
                        pass
                    self._follow_writer = None
            await asyncio.sleep(self.retry_period / 2)

    async def _consume(self, reader: asyncio.StreamReader) -> None:
        """Apply one replication stream. Snapshot frames are buffered and
        applied ONLY when the END trailer validates the count — a stream
        that dies mid-snapshot leaves local state untouched (discard and
        re-request; never serve from a torn snapshot)."""
        from kubernetes_tpu.apiserver.http import decode_object

        st = self.store
        m = _metrics()
        snap_rv: int | None = None
        snap_epoch = 0
        snap_items: list[dict] = []
        try:
            while not self._stopped:
                raw = await reader.readline()
                if not raw:
                    return  # connection ended (torn snapshot handled below)
                rec = json.loads(raw)
                op = rec.get("op")
                if op == "NACK":
                    epoch = int(rec.get("epoch", 0) or 0)
                    if epoch > st.epoch:
                        st.epoch = epoch
                        st.known_primary = (epoch, rec.get("endpoint", ""))
                    return
                if op == "EPOCH":
                    epoch = int(rec.get("epoch", 0) or 0)
                    if epoch < st.epoch:
                        return  # stale primary: drop it, re-resolve
                    st.epoch = epoch
                    st.known_primary = (epoch, rec.get("endpoint", ""))
                    m["epoch"].set(epoch)
                elif op == "SNAP":
                    snap_rv = int(rec["rv"])
                    snap_epoch = int(rec.get("epoch", 0) or 0)
                    snap_items = []
                elif op == "OBJ":
                    if snap_rv is None:
                        return  # OBJ outside a snapshot: broken frame
                    snap_items.append(rec)
                elif op == "END":
                    if snap_rv is None:
                        return
                    if int(rec.get("count", -1)) != len(snap_items):
                        self.snapshots_discarded += 1
                        m["snapshots"].labels("discarded").inc()
                        snap_rv, snap_items = None, []
                        return  # short-counted frame: discard, re-request
                    objs = []
                    for item in snap_items:
                        obj = decode_object(item["kind"], item["obj"])
                        obj.metadata.resource_version = str(int(item["rv"]))
                        objs.append((item["kind"], item["ns"], item["name"],
                                     int(item["rv"]), obj))
                    st.reset_from_snapshot(objs, snap_rv,
                                           snap_epoch=snap_epoch)
                    self.catchups += 1
                    m["snapshots"].labels("applied").inc()
                    snap_rv, snap_items = None, []
                else:  # PUT / DELETE record
                    if snap_rv is not None:
                        # a data record inside an unterminated snapshot:
                        # the frame broke — never apply any of it
                        self.snapshots_discarded += 1
                        m["snapshots"].labels("discarded").inc()
                        snap_rv, snap_items = None, []
                        return
                    rec_epoch = int(rec.get("epoch", 0) or 0)
                    if rec_epoch < st.epoch:
                        m["records"].labels("rejected").inc()
                        return  # stale-epoch record: drop the stream
                    ev = decode_record(rec)
                    if ev.resource_version <= st._rv:
                        continue  # overlap with the catch-up: dedup by rv
                    st.apply_replicated(ev, epoch=rec_epoch)
                    m["records"].labels("applied").inc()
        finally:
            if snap_rv is not None:
                # the primary died before the END trailer arrived: the
                # buffered partial snapshot is DISCARDED — local state was
                # never touched, and the reconnect re-requests in full
                self.snapshots_discarded += 1
                m["snapshots"].labels("discarded").inc()

    # ---- helpers ----

    async def wait_rv(self, rv: int, timeout: float = 10.0) -> bool:
        """Poll until this replica's clock reaches `rv` (tests/drills)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.store._rv >= rv:
                return True
            await asyncio.sleep(0.01)
        return self.store._rv >= rv
