"""In-memory object store with optimistic concurrency and watch streams.

The host-plane analog of the reference's storage stack: etcd3 revisions + CAS
(GuaranteedUpdate, apiserver/pkg/storage/etcd3/store.go:257), the generic
registry's CRUD semantics (registry/generic/registry/store.go:78), the watch
cache ring buffer fanning one stream out to N subscribers
(storage/cacher.go:141, watch_cache.go:93), and the pods/binding subresource
(pkg/registry/core/pod/rest). Storage is a dict of cloned API objects; a
single global monotonically increasing resourceVersion orders all writes, and
watchers can resume from any version still inside the ring buffer — older
versions raise Expired (HTTP 410 analog) which makes clients relist, exactly
the Reflector contract (client-go/tools/cache/reflector.go:239).

Designed to run inside one asyncio loop: CRUD is synchronous (dict ops are
atomic per loop tick), watch delivery is via asyncio.Queue.
"""

from __future__ import annotations

import asyncio
import fnmatch
import logging
import os
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable

from kubernetes_tpu.api.objects import Binding

log = logging.getLogger(__name__)

# bulk native bind (native/commitops.c, -DKTPU_HAVE_PYTHON): one C pass
# over a Binding batch replacing bind_many's per-pod Python loop. None on
# machines without cc/Python.h — bind_many degrades to the Python loop
# (one warning) so tier-1 passes without a C toolchain. KTPU_NATIVE_BIND=0
# forces the fallback (used by the bit-parity tests).
try:
    from kubernetes_tpu.native import bulk_bind as _native_bulk_bind
except Exception:  # pragma: no cover — native layer is strictly best-effort
    _native_bulk_bind = None

if os.environ.get("KTPU_NATIVE_BIND", "") in ("0", "false"):
    _native_bulk_bind = None

_bind_fallback_warned = False


def _warn_bind_fallback() -> None:
    global _bind_fallback_warned
    if not _bind_fallback_warned:
        _bind_fallback_warned = True
        log.warning("native bulk bind unavailable; pods/binding falls back "
                    "to the pure-Python per-pod path")


class NotFound(KeyError):
    pass


class AlreadyExists(ValueError):
    pass


class Conflict(ValueError):
    """resourceVersion mismatch — the CAS failure (etcd3 txn miss)."""


class Expired(ValueError):
    """Watch resume point fell out of the ring buffer (HTTP 410 Gone)."""


class TooManyRequests(ValueError):
    """HTTP 429 — an eviction refused by a disruption budget."""


class FencedWrite(ConnectionError):
    """Write rejected by the replication fencing guard: this replica is a
    standby, or a deposed primary whose epoch token has been superseded
    (apiserver/replication.py). Subclasses ConnectionError deliberately —
    "this endpoint cannot serve the write, go elsewhere" is a transport-
    level failover signal, and every retry loop in the tree already knows
    how to route around one. Carries the newer epoch and the current
    primary's apiserver endpoint ("host:port", possibly empty when the
    rejecting replica cannot reach the coordination quorum either) so
    clients chase the primary instead of backing off blindly."""

    def __init__(self, message: str, epoch: int = 0, endpoint: str = ""):
        super().__init__(message)
        self.epoch = int(epoch)
        self.endpoint = endpoint


@dataclass
class WatchEvent:
    type: str          # ADDED | MODIFIED | DELETED
    kind: str
    obj: Any           # stored instance — consumers must not mutate
    resource_version: int


def _key(namespace: str, name: str) -> tuple[str, str]:
    return (namespace or "default", name)


# end-of-stream marker delivered to an evicted watcher's queue: the stream
# drains buffered events, sees this, and terminates (consumer relists)
_EVICTED = object()


class _Watcher:
    """One watch subscriber: kind filter + bounded delivery queue.

    A subscriber that stops consuming would otherwise buffer every event
    forever; when its queue overflows the store EVICTS it — stream ends,
    client relists — the watch cache's terminate-blocked-watchers behavior
    (storage/cacher.go:1261)."""

    __slots__ = ("kind", "queue", "evicted")

    def __init__(self, kind: str | None, maxsize: int):
        self.kind = kind
        self.queue: asyncio.Queue = asyncio.Queue(maxsize)
        self.evicted = False


_mx_evicted = None


def _watch_evictions():
    global _mx_evicted
    if _mx_evicted is None:
        from kubernetes_tpu.obs import metrics as m

        _mx_evicted = m.REGISTRY.counter(
            "store_watchers_evicted_total",
            "Watch subscribers evicted for exceeding the per-watcher "
            "queue bound (slow consumers must relist).")
    return _mx_evicted


class ObjectStore:
    """One store instance == one apiserver+etcd.

    `persist_path` enables etcd-like durability: every mutation appends one
    JSON line to a write-ahead log (flushed per write, so state survives a
    SIGKILL'd process), and a fresh store replays the log on startup —
    resourceVersions continue from where they stopped, so resumed watchers
    and relisting Reflectors see one consistent history (the checkpoint/
    resume model of SURVEY.md §5.4: components are crash-only, *all* state
    lives in the store). Compaction = delete the log once the cluster is
    drained; replay cost is linear in total writes."""

    def __init__(self, watch_window: int = 4096,
                 persist_path: str | None = None, admission=None,
                 watcher_queue_limit: int | None = None,
                 snapshot_every: int = 0):
        self._objects: dict[str, dict[tuple[str, str], Any]] = {}
        self._rv = 0
        self._history: deque[WatchEvent] = deque(maxlen=watch_window)
        # per-watcher queue bound: a consumer that falls this many events
        # behind is evicted rather than buffered unboundedly (0 disables).
        # Defaults to the history window — a watcher that far behind could
        # not resume from its last seen version anyway
        self._watcher_queue_limit = watch_window \
            if watcher_queue_limit is None else watcher_queue_limit
        self._watchers: list[_Watcher] = []
        self._wal = None
        self._cluster_ip_counter = 0
        # store-side watch fan-out cost: one count per event put onto one
        # subscriber queue. With the WatchCache in front, the store has ONE
        # subscriber and this advances exactly once per published event no
        # matter how many HTTP watchers exist — the fan-out drill's counter
        self.fanout_puts = 0
        # event taps: synchronous callbacks invoked once per published
        # event, after WAL + history, in rv order — the multiproc ring
        # writer hangs here (apiserver/multiproc.py). A tap must never
        # raise and must not mutate the event. O(events) like fanout_puts:
        # taps see each event exactly once regardless of subscriber count
        self.event_taps: list[Callable[[WatchEvent], None]] = []
        # snapshot-backed WAL: after `snapshot_every` log appends, compact()
        # writes a snapshot and truncates the log (0 = manual compact only)
        self.snapshot_every = snapshot_every
        self.compactions = 0
        self._wal_records = 0
        self._persist_path = persist_path
        # admission chain (apiserver/admission.py) applied to create/update
        # — the reference's handler-chain position in front of the registry
        self.admission = admission
        if persist_path:
            snap_rv, snap_valid = self._load_snapshot(persist_path + ".snap")
            self._replay_wal(persist_path,
                             min_rv=snap_rv if snap_valid else 0)
            self._wal = open(persist_path, "a", encoding="utf-8")

    # ---- write-ahead log ----

    def _load_snapshot(self, snap_path: str) -> tuple[int, bool]:
        """Load a compaction snapshot -> (snapshot rv, trailer valid).

        Snapshot format is JSON lines: a SNAP header carrying the
        resourceVersion at snapshot time, one OBJ line per stored object,
        and an END trailer with the object count. Torn snapshots (crash/
        truncation mid-write — the tmp+rename protocol makes this rare but
        a torn tail is still possible on some filesystems) keep the valid
        prefix, exactly the WAL's torn-record contract; an invalid trailer
        additionally disables the WAL's rv-guard so no record is skipped
        on the strength of a snapshot that cannot vouch for itself."""
        import json
        import os

        from kubernetes_tpu.apiserver.http import decode_object

        if not os.path.exists(snap_path):
            return 0, True
        snap_rv = 0
        loaded = skipped = 0
        expected: int | None = None
        with open(snap_path, encoding="utf-8", errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    entry = json.loads(line)
                    op = entry["op"]
                    if op == "SNAP":
                        snap_rv = int(entry["rv"])
                        continue
                    if op == "END":
                        expected = int(entry["count"])
                        break
                    obj = decode_object(entry["kind"], entry["obj"])
                    obj.metadata.resource_version = str(int(entry["rv"]))
                    self._bucket(entry["kind"])[
                        (entry["ns"], entry["name"])] = obj
                    if entry["kind"] == "Service":
                        self._reserve_cluster_ip(
                            obj.spec.get("clusterIP", ""))
                    self._rv = max(self._rv, int(entry["rv"]))
                except Exception:  # noqa: BLE001 — keep the valid prefix
                    skipped += 1
                    continue
                loaded += 1
        valid = expected is not None and expected == loaded and not skipped
        self._rv = max(self._rv, snap_rv if valid else 0)
        if not valid:
            log.warning(
                "torn snapshot %s: loaded %d objects (trailer %s, %d "
                "corrupt lines); replaying the full WAL on top",
                snap_path, loaded, expected, skipped)
        return snap_rv, valid

    def _replay_wal(self, path: str, min_rv: int = 0) -> None:
        import json
        import os

        from kubernetes_tpu.apiserver.http import decode_object

        if not os.path.exists(path):
            return
        # errors="replace" so a crash that tore a multi-byte character in
        # half cannot abort the whole replay with UnicodeDecodeError — the
        # mangled record then fails json parsing and is skipped like any
        # other torn tail write
        recovered = skipped = 0
        with open(path, encoding="utf-8", errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    entry = json.loads(line)
                    kind = entry["kind"]
                    rv = int(entry["rv"])
                    if rv <= min_rv:
                        # predates the snapshot: a crash between the
                        # snapshot rename and the WAL truncate leaves the
                        # old log behind; the snapshot already holds this
                        # state (rv-guarded only when its trailer is valid)
                        continue
                    if entry["op"] == "DELETE":
                        self._bucket(kind).pop(
                            (entry["ns"], entry["name"]), None)
                    else:
                        obj = decode_object(kind, entry["obj"])
                        obj.metadata.resource_version = str(rv)
                        self._bucket(kind)[(entry["ns"], entry["name"])] = obj
                        if kind == "Service":
                            self._reserve_cluster_ip(
                                obj.spec.get("clusterIP", ""))
                except Exception:  # noqa: BLE001 — crash recovery keeps the
                    # valid prefix: a torn/truncated/corrupt record (bad
                    # json, missing fields, undecodable object) is skipped,
                    # never fatal — losing the tail write is the WAL's
                    # contract, losing the whole log is not
                    skipped += 1
                    continue
                recovered += 1
                self._rv = max(self._rv, rv)
        if skipped:
            log.warning("WAL replay: recovered %d records, skipped %d "
                        "corrupt/torn records", recovered, skipped)

    def _append_wal(self, event: WatchEvent, flush: bool = True) -> None:
        import json

        obj = event.obj
        entry = {
            "op": "DELETE" if event.type == "DELETED" else "PUT",
            "rv": event.resource_version,
            "kind": event.kind,
            "ns": obj.metadata.namespace or "default",
            "name": obj.metadata.name,
        }
        if event.type != "DELETED":
            entry["obj"] = obj.to_dict()
        self._wal.write(json.dumps(entry) + "\n")
        if flush:
            self._wal.flush()
        self._wal_records += 1
        if self.snapshot_every and self._wal_records >= self.snapshot_every:
            self.compact()

    def compact(self) -> None:
        """Revision compaction: snapshot the live object set and truncate
        the WAL (etcd's compact+snapshot collapsed into one step — replay
        cost and log size become proportional to live state, not total
        writes, so a week-long churn run doesn't grow the log unboundedly).

        Crash-safe: the snapshot is written to a tmp file, fsynced, and
        atomically renamed before the log truncates. A crash between the
        rename and the truncate leaves stale WAL records behind; recovery
        skips records at or below the snapshot's revision (only when the
        snapshot trailer validates — a torn snapshot replays everything,
        preferring a double-apply over data loss)."""
        import json
        import os

        if not self._persist_path:
            return
        snap_path = self._persist_path + ".snap"
        tmp_path = snap_path + ".tmp"
        count = 0
        with open(tmp_path, "w", encoding="utf-8") as f:
            f.write(json.dumps({"op": "SNAP", "rv": self._rv}) + "\n")
            for kind, bucket in self._objects.items():
                for (ns, name), obj in bucket.items():
                    f.write(json.dumps({
                        "op": "OBJ", "kind": kind, "ns": ns, "name": name,
                        "rv": int(obj.metadata.resource_version or 0),
                        "obj": obj.to_dict()}) + "\n")
                    count += 1
            f.write(json.dumps({"op": "END", "count": count}) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp_path, snap_path)
        if self._wal is not None:
            self._wal.close()
            self._wal = open(self._persist_path, "w", encoding="utf-8")
        self._wal_records = 0
        self.compactions += 1

    def _allocate_node_ports(self, svc) -> None:
        """NodePort allocation from the conventional 30000-32767 range for
        type=NodePort/LoadBalancer ports without one; explicit values must
        be in range and not held by another service (the service
        registry's portallocator, pkg/registry/core/service)."""
        from kubernetes_tpu.apiserver.validation import ValidationError

        if svc.spec.get("type") not in ("NodePort", "LoadBalancer"):
            return
        key = _key(svc.metadata.namespace, svc.metadata.name)
        used = {int(p.get("nodePort") or 0)
                for other_key, other in self._bucket("Service").items()
                if other_key != key
                for p in other.spec.get("ports") or []}
        used.discard(0)
        explicit: set[int] = set()
        for p in svc.spec.get("ports") or []:
            node_port = int(p.get("nodePort") or 0)
            if not node_port:
                continue
            if not 30000 <= node_port < 32768:
                raise ValidationError(
                    f"spec.ports.nodePort: {node_port} is out of range "
                    f"30000-32767")
            if node_port in used or node_port in explicit:
                raise ValidationError(
                    f"spec.ports.nodePort: provided port {node_port} is "
                    f"already allocated")
            explicit.add(node_port)
        used |= explicit
        nxt = 30000
        for p in svc.spec.get("ports") or []:
            if int(p.get("nodePort") or 0):
                continue
            while nxt in used and nxt < 32768:
                nxt += 1
            if nxt >= 32768:
                raise ValidationError("node port range exhausted")
            p["nodePort"] = nxt
            used.add(nxt)

    def _reserve_cluster_ip(self, ip: str) -> None:
        """Advance the allocator past an explicitly-given clusterIP so a
        later auto-allocation cannot hand out a duplicate."""
        if not ip.startswith("10.96."):
            return
        try:
            _z, _z2, a, b = ip.split(".")
            self._cluster_ip_counter = max(self._cluster_ip_counter,
                                           int(a) * 250 + int(b) - 1)
        except ValueError:
            pass

    # ---- versioning ----

    @property
    def resource_version(self) -> int:
        return self._rv

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    # ---- CRUD ----

    def _bucket(self, kind: str) -> dict[tuple[str, str], Any]:
        return self._objects.setdefault(kind, {})

    def create(self, obj: Any, *, copy: bool = True) -> Any:
        """copy=False stores the caller's instance directly (the caller
        relinquishes it — used by trusted in-process writers like the event
        recorder to skip two deep clones per object)."""
        kind = obj.kind
        key = _key(obj.metadata.namespace, obj.metadata.name)
        bucket = self._bucket(kind)
        if key in bucket:
            raise AlreadyExists(f"{kind} {key} already exists")
        stored = obj.clone() if copy else obj
        # validation precedes admission so plugins with side effects (the
        # quota usage mirror) never observe an object the write path will
        # reject anyway; admission-added defaults come from trusted config
        # objects that were themselves validated on THEIR write
        from kubernetes_tpu.apiserver.validation import validate

        validate(stored)
        if self.admission is not None:
            self.admission.admit(self, stored, "CREATE")
        rv = self._next_rv()
        stored.metadata.resource_version = str(rv)
        stored.metadata.creation_timestamp = time.time()
        if kind == "Service":
            if stored.spec.get("clusterIP"):
                self._reserve_cluster_ip(stored.spec["clusterIP"])
            else:
                # the service registry's ClusterIP allocation
                # (pkg/registry/core/service/ipallocator) — sequential from
                # the conventional service CIDR
                self._cluster_ip_counter += 1
                c = self._cluster_ip_counter
                stored.spec["clusterIP"] = f"10.96.{c // 250}.{c % 250 + 1}"
            self._allocate_node_ports(stored)
        bucket[key] = stored
        # watch consumers get the stored instance itself and MUST NOT mutate
        # it (same contract as client-go informer caches)
        self._publish(WatchEvent("ADDED", kind, stored, rv))
        return stored.clone() if copy else stored

    def create_many(self, objs: list[Any]) -> list[Any]:
        """Bulk create for trusted high-volume in-process writers (the event
        recorder's batch path). Per-object semantics match create(copy=False)
        — validation, admission, allocation, one rv + ADDED event each —
        with the per-call overhead (bucket/watcher lookups, WAL flush)
        amortized across the batch. Objects that fail validation/admission/
        uniqueness raise immediately, after earlier objects in the batch
        have already committed (same as a serial loop)."""
        from kubernetes_tpu.apiserver.validation import validate

        out: list[Any] = []
        events: list[WatchEvent] = []
        now = time.time()
        try:
            for stored in objs:
                kind = stored.kind
                if kind == "Service":
                    # delegate to create() for the allocator path (bulk
                    # writers are Events in practice; create() validates and
                    # admits itself); flush first so watch order matches
                    # write order
                    self._flush_created(events)
                    out.append(self.create(stored, copy=False))
                    continue
                bucket = self._bucket(kind)
                key = _key(stored.metadata.namespace, stored.metadata.name)
                if key in bucket:
                    raise AlreadyExists(f"{kind} {key} already exists")
                validate(stored)
                if self.admission is not None:
                    self.admission.admit(self, stored, "CREATE")
                self._rv += 1
                stored.metadata.resource_version = str(self._rv)
                stored.metadata.creation_timestamp = now
                bucket[key] = stored
                events.append(WatchEvent("ADDED", kind, stored, self._rv))
                out.append(stored)
        finally:
            self._flush_created(events)
        return out

    def _flush_created(self, events: list[WatchEvent]) -> list:
        """Publish a pending bulk-create event batch (WAL once, then the
        watcher queues); returns [] so callers can reset their batch."""
        if not events:
            return []
        if self._wal is not None:
            for ev in events:
                self._append_wal(ev, flush=False)
            self._wal.flush()
        self._history.extend(events)
        if self.event_taps:
            for ev in events:
                for tap in self.event_taps:
                    tap(ev)
        for watcher in list(self._watchers):
            kind = watcher.kind
            put = watcher.queue.put_nowait
            try:
                for ev in events:
                    if kind is None or kind == ev.kind:
                        put(ev)
                        self.fanout_puts += 1
            except asyncio.QueueFull:
                self._evict_watcher(watcher)
        events.clear()
        return []

    def get(self, kind: str, name: str, namespace: str = "default") -> Any:
        try:
            return self._bucket(kind)[_key(namespace, name)].clone()
        except KeyError:
            raise NotFound(f"{kind} {namespace}/{name} not found") from None

    def update(self, obj: Any, *, check_version: bool = True) -> Any:
        kind = obj.kind
        key = _key(obj.metadata.namespace, obj.metadata.name)
        bucket = self._bucket(kind)
        current = bucket.get(key)
        if current is None:
            raise NotFound(f"{kind} {key} not found")
        if check_version and obj.metadata.resource_version and (
                obj.metadata.resource_version != current.metadata.resource_version):
            raise Conflict(
                f"{kind} {key}: version {obj.metadata.resource_version} != "
                f"{current.metadata.resource_version}")
        stored = obj.clone()
        from kubernetes_tpu.apiserver.validation import validate

        validate(stored)
        if self.admission is not None:
            self.admission.admit(self, stored, "UPDATE")
        rv = self._next_rv()
        stored.metadata.resource_version = str(rv)
        stored.metadata.creation_timestamp = current.metadata.creation_timestamp
        if current.metadata.deletion_timestamp is not None:
            # terminating is one-way: an update cannot "undelete"
            stored.metadata.deletion_timestamp = \
                current.metadata.deletion_timestamp
        if kind == "Service" and not stored.spec.get("clusterIP"):
            # clusterIP is allocate-once, immutable: a spec-replacing update
            # (kubectl apply) must not wipe it (service strategy
            # PrepareForUpdate)
            ip = current.spec.get("clusterIP")
            if ip:
                stored.spec["clusterIP"] = ip
        if kind == "Service":
            if stored.spec.get("type") in ("NodePort", "LoadBalancer"):
                # nodePorts are allocate-once: an update that drops them
                # re-inherits by (port, protocol), then fills gaps
                have = {(int(p.get("port") or 0),
                         p.get("protocol", "TCP")):
                        int(p.get("nodePort") or 0)
                        for p in current.spec.get("ports") or []}
                for p in stored.spec.get("ports") or []:
                    if not int(p.get("nodePort") or 0):
                        inherited = have.get((int(p.get("port") or 0),
                                              p.get("protocol", "TCP")))
                        if inherited:
                            p["nodePort"] = inherited
                self._allocate_node_ports(stored)
            else:
                # NodePort -> ClusterIP releases the ports (the reference
                # registry strips them on that transition)
                for p in stored.spec.get("ports") or []:
                    p.pop("nodePort", None)
        # a terminating object whose last finalizer was just removed is
        # finalized: it leaves the store now (DELETED, not MODIFIED).
        # Gated on the PRIOR object having had finalizers, so soft-deletes
        # that never used finalizers (the namespace phase flow) update
        # normally
        if current.metadata.deletion_timestamp is not None \
                and current.metadata.finalizers \
                and not stored.metadata.finalizers:
            bucket.pop(key, None)
            self._publish(WatchEvent("DELETED", kind, stored, rv))
            return stored.clone()
        bucket[key] = stored
        self._publish(WatchEvent("MODIFIED", kind, stored, rv))
        return stored.clone()

    def patch(self, kind: str, name: str, namespace: str, patch,
              content_type: str, retries: int = 5) -> Any:
        """PATCH verb (apiserver/pkg/endpoints/handlers/patch.go:51):
        apply a strategic-merge / merge / JSON patch to the live object
        and CAS the result back, retrying on write conflicts like the
        reference handler (rides guaranteed_update — one CAS policy). A
        patch that pins metadata.resourceVersion to a stale version is a
        hard 409 (raised from the transform, so no retry) — that is the
        optimistic-concurrency contract kubectl apply relies on."""
        from kubernetes_tpu.apiserver.http import decode_object, encode_object
        from kubernetes_tpu.apiserver.strategicpatch import apply_patch

        pinned = None
        if isinstance(patch, dict):
            pinned = (patch.get("metadata") or {}).get("resourceVersion")

        def transform(current):
            if pinned and pinned != current.metadata.resource_version:
                raise Conflict(
                    f"{kind} {namespace}/{name}: patch resourceVersion "
                    f"{pinned} != {current.metadata.resource_version}")
            merged = apply_patch(encode_object(current), patch,
                                 content_type)
            # identity fields never patch away
            merged.setdefault("metadata", {})["name"] = name
            merged["metadata"]["namespace"] = namespace
            obj = decode_object(kind, merged)
            obj.metadata.resource_version = \
                current.metadata.resource_version
            return obj

        return self.guaranteed_update(kind, name, namespace, transform,
                                      retries=retries)

    def guaranteed_update(self, kind: str, name: str, namespace: str,
                          mutate: Callable[[Any], Any], retries: int = 16) -> Any:
        """CAS retry loop (GuaranteedUpdate, etcd3/store.go:257). `mutate`
        may update the object in place, or return a replacement; an
        exception it raises (including Conflict for a pinned stale
        version) aborts the loop."""
        for _ in range(retries):
            obj = self.get(kind, name, namespace)
            replacement = mutate(obj)
            if replacement is not None:
                obj = replacement
            try:
                return self.update(obj)
            except Conflict:
                continue
        raise Conflict(f"{kind} {namespace}/{name}: too many CAS retries")

    def delete(self, kind: str, name: str, namespace: str = "default") -> Any:
        bucket = self._bucket(kind)
        key = _key(namespace, name)
        obj = bucket.get(key)
        if obj is None:
            raise NotFound(f"{kind} {namespace}/{name} not found")
        if obj.metadata.finalizers:
            # finalization: mark terminating and wait — the object is
            # removed only when the last finalizer is cleared by an update
            # (generic registry deletion flow, store.go; the GC's
            # blockOwnerDeletion rides this)
            if obj.metadata.deletion_timestamp is None:
                marked = obj.clone()
                marked.metadata.deletion_timestamp = time.time()
                rv = self._next_rv()
                marked.metadata.resource_version = str(rv)
                bucket[key] = marked
                self._publish(WatchEvent("MODIFIED", kind, marked, rv))
                return marked.clone()
            return obj.clone()  # already terminating: idempotent
        bucket.pop(key)
        rv = self._next_rv()
        self._publish(WatchEvent("DELETED", kind, obj, rv))
        return obj.clone()

    def list(self, kind: str, namespace: str | None = None,
             label_selector: dict[str, str] | None = None,
             field_glob: str | None = None, *,
             copy_objects: bool = True) -> list[Any]:
        """copy_objects=False shares stored instances (read-only contract) —
        used by informers, matching client-go cache semantics."""
        out = []
        for (ns, name), obj in self._bucket(kind).items():
            if namespace is not None and ns != namespace:
                continue
            if label_selector is not None:
                labels = obj.metadata.labels
                if not all(labels.get(k) == v for k, v in label_selector.items()):
                    continue
            if field_glob is not None and not fnmatch.fnmatch(name, field_glob):
                continue
            out.append(obj.clone() if copy_objects else obj)
        return out

    def list_with_version(self, kind: str) -> tuple[list[Any], int]:
        """(items, resourceVersion) as ONE consistent snapshot — the List
        half of ListAndWatch (a separate resource_version read would let
        events slip between the two and be delivered twice on resume)."""
        return self.list(kind, copy_objects=False), self._rv

    # ---- pods/binding subresource ----

    def bind_many(self, bindings: list[Binding]) -> tuple[list, list]:
        """Batched pods/binding subresource: the whole batch binds in one
        synchronous pass (the bulk path the batch scheduler drives; each pod
        still gets its own resourceVersion and MODIFIED event, so watch
        consumers observe exactly the serial-bind history). Per-pod failures
        don't fail the batch: returns (bound, errors) parallel to
        `bindings`, one of each per entry non-None.

        Semantics preserved from bind() / the reference binding REST
        (pkg/registry/core/pod/rest/subresources.go:87): not-found -> error,
        already-bound -> conflict, spec.nodeName set exactly once. The
        amortizations (hoisted bucket/watcher lookups, one WAL flush, shared
        immutable innards) are why this exists: the serial path's per-pod
        cost was the measured e2e throughput wall (PERF.md)."""

        def shell(obj):
            # shallow dataclass copy without copy.copy's reduce/dispatch
            # machinery (~10x cheaper; this loop is the e2e hot path)
            new = obj.__class__.__new__(obj.__class__)
            new.__dict__.update(obj.__dict__)
            return new

        bucket = self._bucket("Pod")
        pod_watchers = [w for w in self._watchers
                        if w.kind is None or w.kind == "Pod"]
        if (_native_bulk_bind is not None and type(bucket) is dict
                and type(bindings) is list):
            # one C pass builds the shells, the rebound pods, the bucket
            # writes and the watch fan-out buffer (native/commitops.c
            # ktpu_bulk_bind; bit-parity pinned by tests/test_native_bind)
            bound, errors, events, self._rv = _native_bulk_bind(
                bucket, bindings, self._rv, WatchEvent, NotFound, Conflict)
        else:
            _warn_bind_fallback()
            bound = []
            errors = []
            events = []
            for binding in bindings:
                key = _key(binding.namespace, binding.pod_name)
                current = bucket.get(key)
                if current is None:
                    bound.append(None)
                    errors.append(NotFound(
                        f"Pod {binding.namespace}/{binding.pod_name} "
                        f"not found"))
                    continue
                if current.spec.node_name:
                    bound.append(None)
                    errors.append(Conflict(
                        f"pod {binding.namespace}/{binding.pod_name} already "
                        f"bound to {current.spec.node_name}"))
                    continue
                self._rv += 1
                rv = self._rv
                meta = shell(current.metadata)
                meta.resource_version = str(rv)
                spec = shell(current.spec)
                spec.node_name = binding.target_node
                stored = type(current)(metadata=meta, spec=spec,
                                       status=current.status)
                bucket[key] = stored
                events.append(WatchEvent("MODIFIED", "Pod", stored, rv))
                bound.append(stored)
                errors.append(None)
        if self._wal is not None and events:
            for ev in events:
                self._append_wal(ev, flush=False)
            self._wal.flush()
        self._history.extend(events)
        if self.event_taps:
            for ev in events:
                for tap in self.event_taps:
                    tap(ev)
        for watcher in pod_watchers:
            put = watcher.queue.put_nowait
            try:
                for ev in events:
                    put(ev)
                    self.fanout_puts += 1
            except asyncio.QueueFull:
                self._evict_watcher(watcher)
        return bound, errors

    def bind(self, binding: Binding) -> Any:
        """Set spec.nodeName exactly once (the scheduler's write; reference
        registry rejects double binds).

        Hot path for the batch scheduler: the rebound pod shares its
        immutable innards (containers, labels, tolerations, status) with the
        previous stored instance — only the mutated shells (spec, metadata)
        are fresh. Safe under the same watch-consumer read-only contract as
        the informer caches; three deep clones per bind were the largest
        single cost of the bind loop at bench scale."""
        import dataclasses

        bucket = self._bucket("Pod")
        key = _key(binding.namespace, binding.pod_name)
        current = bucket.get(key)
        if current is None:
            raise NotFound(
                f"Pod {binding.namespace}/{binding.pod_name} not found")
        if current.spec.node_name:
            raise Conflict(
                f"pod {binding.namespace}/{binding.pod_name} already bound "
                f"to {current.spec.node_name}")
        rv = self._next_rv()
        stored = type(current)(
            metadata=dataclasses.replace(current.metadata,
                                         resource_version=str(rv)),
            spec=dataclasses.replace(current.spec,
                                     node_name=binding.target_node),
            status=current.status)
        bucket[key] = stored
        self._publish(WatchEvent("MODIFIED", "Pod", stored, rv))
        return stored

    # ---- multiproc mirror ----

    def apply_external_event(self, event: WatchEvent) -> None:
        """Mirror-apply one event from an external authority (the
        multiproc shared-memory ring): update the bucket, advance the rv
        clock, append history, fan out to local watchers. No WAL, no
        validation/admission, no taps — the owner process already did all
        of that; this store is a read replica and events arrive strictly
        in rv order (single writer, single sequence)."""
        obj = event.obj
        key = _key(obj.metadata.namespace, obj.metadata.name)
        bucket = self._bucket(event.kind)
        if event.type == "DELETED":
            bucket.pop(key, None)
        else:
            bucket[key] = obj
            if event.kind == "Service":
                self._reserve_cluster_ip(obj.spec.get("clusterIP", ""))
        self._rv = max(self._rv, event.resource_version)
        self._history.append(event)
        for watcher in list(self._watchers):
            if watcher.kind is None or watcher.kind == event.kind:
                try:
                    watcher.queue.put_nowait(event)
                    self.fanout_puts += 1
                except asyncio.QueueFull:
                    self._evict_watcher(watcher)

    # ---- watch ----

    def _publish(self, event: WatchEvent) -> None:
        if self._wal is not None:
            self._append_wal(event)
        self._history.append(event)
        for tap in self.event_taps:
            tap(event)
        for watcher in list(self._watchers):
            if watcher.kind is None or watcher.kind == event.kind:
                try:
                    watcher.queue.put_nowait(event)
                    self.fanout_puts += 1
                except asyncio.QueueFull:
                    self._evict_watcher(watcher)

    def _evict_watcher(self, watcher: _Watcher) -> None:
        """Terminate one subscriber: unsubscribe it, mark it evicted, and
        (best effort) enqueue the end-of-stream sentinel so a consumer
        blocked in queue.get() wakes immediately. Its stream drains any
        buffered events, then ends — the consumer relists."""
        try:
            self._watchers.remove(watcher)
        except ValueError:
            return  # already evicted/stopped
        watcher.evicted = True
        if watcher.queue.full():
            # drop the oldest buffered event so the sentinel lands NOW: a
            # consumer blocked in next() must learn of eviction promptly,
            # not after draining the whole backlog (it relists anyway)
            try:
                watcher.queue.get_nowait()
            except asyncio.QueueEmpty:
                pass
        try:
            watcher.queue.put_nowait(_EVICTED)
        except asyncio.QueueFull:
            pass
        _watch_evictions().inc()

    def _detach_watcher(self, watcher: _Watcher) -> None:
        """End one subscriber WITHOUT counting an eviction — the graceful
        replica-drain path (the subscriber did nothing wrong; the eviction
        counter must keep meaning "slow consumer")."""
        try:
            self._watchers.remove(watcher)
        except ValueError:
            return
        watcher.evicted = True
        if watcher.queue.full():
            try:
                watcher.queue.get_nowait()  # drop-oldest: sentinel lands now
            except asyncio.QueueEmpty:
                pass
        try:
            watcher.queue.put_nowait(_EVICTED)
        except asyncio.QueueFull:
            pass

    def watch(self, kind: str | None = None,
              since: int | None = None) -> "WatchStream":
        """Subscribe to events after resourceVersion `since` (None = now).

        Raises Expired if `since` predates the ring buffer — the caller must
        relist, like a Reflector on 410. A resume backlog that already
        exceeds the per-watcher queue bound is also Expired: delivering it
        would evict the subscriber immediately, so an honest 410 now saves
        the round trip.
        """
        backlog: list[WatchEvent] = []
        if since is not None and since < self._rv:
            oldest = self._history[0].resource_version if self._history else self._rv + 1
            if since < oldest - 1:
                raise Expired(f"resourceVersion {since} is too old "
                              f"(window starts at {oldest})")
            backlog = [e for e in self._history
                       if e.resource_version > since
                       and (kind is None or kind == e.kind)]
        limit = self._watcher_queue_limit
        if limit and len(backlog) >= limit:
            raise Expired(
                f"resume backlog of {len(backlog)} events exceeds the "
                f"{limit}-event watcher bound")
        watcher = _Watcher(kind, limit)
        self._watchers.append(watcher)
        for e in backlog:
            watcher.queue.put_nowait(e)
        return WatchStream(self, watcher, watcher.queue)


class WatchStream:
    def __init__(self, store: ObjectStore, entry: _Watcher,
                 queue: asyncio.Queue):
        self._store = store
        self._entry = entry
        self._queue = queue
        self._stopped = False

    async def next(self, timeout: float | None = None) -> WatchEvent | None:
        if self._stopped:
            return None
        if self._entry.evicted and self._queue.empty():
            # evicted with the backlog fully drained (the sentinel may have
            # been dropped if the queue was full at eviction time)
            self._stopped = True
            return None
        try:
            if timeout is None:
                ev = await self._queue.get()
            else:
                ev = await asyncio.wait_for(self._queue.get(), timeout)
        except asyncio.TimeoutError:
            return None
        if ev is _EVICTED:
            self._stopped = True  # stream over: the consumer must relist
            return None
        return ev

    def stop(self) -> None:
        if not self._stopped:
            self._stopped = True
            try:
                self._store._watchers.remove(self._entry)
            except ValueError:
                pass

    def __aiter__(self):
        return self

    async def __anext__(self) -> WatchEvent:
        ev = await self.next()
        if ev is None:
            raise StopAsyncIteration
        return ev
