from kubernetes_tpu.apiserver.store import (  # noqa: F401
    Conflict,
    NotFound,
    AlreadyExists,
    Expired,
    ObjectStore,
    WatchEvent,
)
