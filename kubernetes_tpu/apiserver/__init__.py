from kubernetes_tpu.apiserver.store import (  # noqa: F401
    Conflict,
    FencedWrite,
    NotFound,
    AlreadyExists,
    Expired,
    ObjectStore,
    WatchEvent,
)
