"""Multi-process control plane: shared-memory event ring + worker procs.

One **store-owner process** keeps the authoritative `ObjectStore` — single
writer, single resourceVersion sequence, WAL as the shared-storage analog
(the etcd position in the reference architecture). `KTPU_WORKER_PROCS`
**worker processes** each run a full serving loop + `KTPU_FANOUT_SHARDS`
delivery threads over a read-only mirror of the store. Two channels cross
the process boundary:

* **Event ring** (`multiprocessing.shared_memory`): the owner appends each
  event's encode-once `_Frame` wire bytes exactly once; every worker mmaps
  the same segment and fans frames out to its watchers with **zero
  per-process re-encode** (the worker's `watchcache_frames_encoded_total`
  stays 0 — the owner's counter is the encode ledger). The ring header
  carries `(min_rv, max_rv)`, so a reader the writer has lapped gets an
  honest 410 → snapshot resync → subscriber relist, never a silent gap.

* **Mutation RPC** (unix-domain socket, newline-delimited JSON): workers
  forward create/update/delete/patch/bind to the owner, which executes
  them against the real store — validation, admission, WAL, exactly-once
  all live there, so a replayed create answers AlreadyExists and a
  replayed bind answers Conflict exactly as today. The owner appends the
  ring record *before* writing the RPC response, so a worker that drains
  the ring to the response's rv (`RingPump.catch_up`) serves
  read-your-writes immediately.

`KTPU_WORKER_PROCS=0` (the default) pins the in-process topology —
byte-parity fallback, and what tier-1 runs.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import signal
import socket
import struct
import tempfile
import threading
import time
from dataclasses import dataclass
from multiprocessing import shared_memory
from typing import Any, Callable

from kubernetes_tpu.api import objects as objs
from kubernetes_tpu.apiserver.store import (
    AlreadyExists,
    Conflict,
    Expired,
    NotFound,
    ObjectStore,
    TooManyRequests,
    WatchEvent,
)

log = logging.getLogger("ktpu.multiproc")


def default_worker_procs() -> int:
    """`KTPU_WORKER_PROCS`: how many apiserver worker processes to run.
    0 (the default) pins today's in-process topology — the tier-1 parity
    fallback."""
    try:
        return max(0, int(os.environ.get("KTPU_WORKER_PROCS", "0")))
    except ValueError:
        return 0


def pin_to_core(worker_id: int) -> int | None:
    """Pin the calling process to one CPU (workers round-robin the
    affinity set). Best-effort: platforms without sched_setaffinity and
    restricted containers simply decline the pin."""
    if not hasattr(os, "sched_setaffinity"):
        return None
    try:
        cpus = sorted(os.sched_getaffinity(0))
        cpu = cpus[worker_id % len(cpus)]
        os.sched_setaffinity(0, {cpu})
        return cpu
    except OSError:
        log.warning("worker %d: sched_setaffinity refused", worker_id)
        return None


def _attach_shm(name: str) -> shared_memory.SharedMemory:
    """Attach to an existing segment. Pre-3.13 SharedMemory has no
    track=False, so attaching registers the segment with the resource
    tracker (bpo-39959) — but in this topology every attacher is a spawn
    CHILD of the creating owner, and spawn children inherit the parent's
    tracker fd, so the attach registration lands in the same dedup'd set
    as the owner's and the owner's unlink/unregister leaves the tracker
    clean. Never attach from a process that is not a descendant of the
    owner: its independent tracker would unlink the segment on exit,
    destroying the ring under everyone else."""
    return shared_memory.SharedMemory(name=name)


# ---- the ring ----
#
# layout (little-endian):
#   [0:64)                     header
#     u32 magic, u32 version
#     u64 head     — byte offset of the oldest retained record
#     u64 tail     — byte offset one past the newest record
#     u64 min_rv   — rv of the record at head (410 floor)
#     u64 max_rv   — rv of the record before tail
#     u64 capacity — data region size in bytes
#     u64 n_slots  — reader slot count
#   [64 : 64+32*n_slots)       reader slots, 32 bytes each:
#     u64 pid, u64 read_pos, u64 last_rv, u64 reserved
#   [data_off : data_off+capacity)  record bytes
#
# head/tail/read_pos are MONOTONIC byte offsets; the physical index is
# offset % capacity, so a record may wrap the physical end in two parts.
# Records are `[u32 len][u64 rv][payload]`. Single writer (the owner);
# readers synchronize with a seqlock: re-check head after copying — if
# head moved past the copy's start, the bytes may be torn → Expired.

_MAGIC = 0x4B545055  # "KTPU"
_VERSION = 1
_HDR = struct.Struct("<II")
_HDR_SIZE = 64
_HEAD_OFF = 8
_TAIL_OFF = 16
_MINRV_OFF = 24
_MAXRV_OFF = 32
_CAP_OFF = 40
_NSLOTS_OFF = 48
_SLOTS_OFF = 64
_SLOT = struct.Struct("<QQQQ")
_REC = struct.Struct("<IQ")  # length, resource_version
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


class EventRing:
    """Single-writer multi-reader byte ring over one SharedMemory segment.

    Writer order matters: head (and min_rv) advance BEFORE the reclaimed
    bytes are overwritten, the record's bytes land before tail moves, and
    tail moves last — so a reader either sees a fully-written record or,
    if the writer lapped it mid-copy, detects the lap from head and raises
    Expired (the honest-410 signal). All header fields are single u64
    stores, atomic under the GIL / a single mmap word."""

    def __init__(self, shm: shared_memory.SharedMemory, *, owner: bool):
        self._shm = shm
        self._buf = shm.buf
        self._owner = owner
        self._closed = False
        magic, version = _HDR.unpack_from(self._buf, 0)
        if owner is False and (magic != _MAGIC or version != _VERSION):
            raise ValueError(
                f"shared segment {shm.name!r} is not a ktpu event ring "
                f"(magic {magic:#x} version {version})")
        self.capacity = self._get_u64(_CAP_OFF)
        self.n_slots = self._get_u64(_NSLOTS_OFF)
        self._data_off = _SLOTS_OFF + _SLOT.size * self.n_slots
        # owner-side O(events) proof: exactly one append per published
        # event, independent of worker/watcher count
        self.appends = 0

    # -- construction --

    @classmethod
    def create(cls, *, name: str | None = None,
               capacity: int = 1 << 22, n_slots: int = 16) -> "EventRing":
        size = _HDR_SIZE + _SLOT.size * n_slots + capacity
        shm = shared_memory.SharedMemory(name=name, create=True, size=size)
        buf = shm.buf
        buf[:size] = b"\x00" * size
        _HDR.pack_into(buf, 0, _MAGIC, _VERSION)
        _U64.pack_into(buf, _CAP_OFF, capacity)
        _U64.pack_into(buf, _NSLOTS_OFF, n_slots)
        return cls(shm, owner=True)

    @classmethod
    def attach(cls, name: str) -> "EventRing":
        return cls(_attach_shm(name), owner=False)

    @property
    def name(self) -> str:
        return self._shm.name

    # -- header accessors --

    def _get_u64(self, off: int) -> int:
        return _U64.unpack_from(self._buf, off)[0]

    def _set_u64(self, off: int, value: int) -> None:
        _U64.pack_into(self._buf, off, value)

    @property
    def head(self) -> int:
        return self._get_u64(_HEAD_OFF)

    @property
    def tail(self) -> int:
        return self._get_u64(_TAIL_OFF)

    @property
    def min_rv(self) -> int:
        return self._get_u64(_MINRV_OFF)

    @property
    def max_rv(self) -> int:
        return self._get_u64(_MAXRV_OFF)

    # -- reader slots --

    def slot(self, i: int) -> tuple[int, int, int]:
        """(pid, read_pos, last_rv) for reader slot i."""
        pid, pos, last_rv, _ = _SLOT.unpack_from(
            self._buf, _SLOTS_OFF + _SLOT.size * i)
        return pid, pos, last_rv

    def set_slot(self, i: int, *, pid: int | None = None,
                 read_pos: int | None = None,
                 last_rv: int | None = None) -> None:
        base = _SLOTS_OFF + _SLOT.size * i
        if pid is not None:
            _U64.pack_into(self._buf, base, pid)
        if read_pos is not None:
            _U64.pack_into(self._buf, base + 8, read_pos)
        if last_rv is not None:
            _U64.pack_into(self._buf, base + 16, last_rv)

    def claim_slot(self, i: int, pid: int) -> None:
        if not 0 <= i < self.n_slots:
            raise ValueError(f"worker id {i} out of range "
                             f"(ring has {self.n_slots} slots)")
        self.set_slot(i, pid=pid)

    def release_slot(self, i: int) -> tuple[int, int]:
        """Clear a dead reader's pid but KEEP read_pos/last_rv — the
        respawned worker's resume bookkeeping. Returns (read_pos,
        last_rv) as observed."""
        _, pos, last_rv = self.slot(i)
        self.set_slot(i, pid=0)
        return pos, last_rv

    # -- modular byte copies --

    def _write_at(self, pos: int, data: bytes) -> None:
        off = self._data_off + pos % self.capacity
        limit = self._data_off + self.capacity
        n = len(data)
        if off + n <= limit:
            self._buf[off:off + n] = data
        else:
            first = limit - off
            self._buf[off:limit] = data[:first]
            self._buf[self._data_off:self._data_off + n - first] = \
                data[first:]

    def _read_at(self, pos: int, n: int) -> bytes:
        off = self._data_off + pos % self.capacity
        limit = self._data_off + self.capacity
        if off + n <= limit:
            return bytes(self._buf[off:off + n])
        first = limit - off
        return bytes(self._buf[off:limit]) + \
            bytes(self._buf[self._data_off:self._data_off + n - first])

    # -- writer (owner only) --

    def append(self, rv: int, payload: bytes) -> None:
        rec = _REC.pack(len(payload), rv) + payload
        need = len(rec)
        if need > self.capacity:
            raise ValueError(
                f"event of {need} bytes exceeds ring capacity "
                f"{self.capacity}")
        head = self.head
        tail = self.tail
        # reclaim whole records until the new one fits; head (and min_rv)
        # move before any reclaimed byte is overwritten, so a lapped
        # reader's seqlock re-check always fires
        while tail + need - head > self.capacity:
            head = self._advance_head(head, tail)
        self._write_at(tail, rec)
        if head == tail:  # ring was empty: this record is now the oldest
            self._set_u64(_MINRV_OFF, rv)
        self._set_u64(_MAXRV_OFF, rv)
        self._set_u64(_TAIL_OFF, tail + need)
        self.appends += 1

    def _advance_head(self, head: int, tail: int) -> int:
        plen = _U32.unpack(self._read_at(head, 4))[0]
        new_head = head + _REC.size + plen
        self._set_u64(_HEAD_OFF, new_head)
        if new_head < tail:
            next_rv = _U64.unpack(self._read_at(new_head + 4, 8))[0]
            self._set_u64(_MINRV_OFF, next_rv)
        return new_head

    # -- reader --

    def read(self, pos: int,
             max_records: int = 1024) -> tuple[int, list[tuple[int, bytes]]]:
        """Read records from monotonic offset `pos`. Returns (new_pos,
        [(rv, payload), ...]); empty list when caught up. Raises Expired
        when the writer has lapped this reader — the caller must resync
        from a snapshot (honest 410, never a silent gap)."""
        tail = self.tail
        if pos >= tail:
            return pos, []
        if pos < self.head:
            raise Expired(
                f"ring overrun: reader at {pos}, window starts at "
                f"{self.head} (min rv {self.min_rv})")
        out: list[tuple[int, bytes]] = []
        while pos < tail and len(out) < max_records:
            plen, rv = _REC.unpack(self._read_at(pos, _REC.size))
            if pos + _REC.size + plen > tail:
                # a valid record never extends past the tail we snapped:
                # the header bytes were torn by a lapping writer
                raise Expired("ring overrun: torn record header")
            payload = self._read_at(pos + _REC.size, plen)
            if pos < self.head:  # seqlock: copy may be torn — discard
                raise Expired(
                    f"ring overrun during read (window starts at "
                    f"{self.head}, min rv {self.min_rv})")
            out.append((rv, payload))
            pos += _REC.size + plen
        return pos, out

    # -- lifetime --

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._buf = None
        self._shm.close()

    def unlink(self) -> None:
        try:
            self._shm.unlink()
        except FileNotFoundError:
            pass


# ---- RPC plumbing ----

def _rpc_exception(name: str, message: str) -> Exception:
    """Rehydrate an owner-side exception by class name (the store's public
    error vocabulary plus validation/admission)."""
    from kubernetes_tpu.apiserver.admission import AdmissionError
    from kubernetes_tpu.apiserver.validation import ValidationError

    table: dict[str, type[Exception]] = {
        "NotFound": NotFound,
        "AlreadyExists": AlreadyExists,
        "Conflict": Conflict,
        "Expired": Expired,
        "TooManyRequests": TooManyRequests,
        "ValidationError": ValidationError,
        "AdmissionError": AdmissionError,
        "PermissionError": PermissionError,
    }
    return table.get(name, ValueError)(message)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


class StoreOwner:
    """Owner-process runtime around the authoritative ObjectStore: the
    ring writer (an event tap — one append per published event, after
    WAL + history, in rv order) and the unix-socket RPC server the
    workers forward mutations to. Lives on the owner's event loop."""

    def __init__(self, store: ObjectStore, *,
                 rpc_path: str | None = None,
                 ring_name: str | None = None,
                 ring_capacity: int = 1 << 22,
                 n_slots: int = 16):
        self.store = store
        self.ring = EventRing.create(name=ring_name,
                                     capacity=ring_capacity,
                                     n_slots=n_slots)
        if rpc_path is None:
            rpc_path = os.path.join(
                tempfile.mkdtemp(prefix="ktpu-mp-"), "owner.sock")
        self.rpc_path = rpc_path
        self._server: asyncio.AbstractServer | None = None
        # the encode-once ledger: wire bytes produced exactly here, once
        # per event, shared by every worker process via the ring
        self.frames_encoded = 0
        self.rpc_requests = 0
        store.event_taps.append(self._ring_tap)

    def _ring_tap(self, event: WatchEvent) -> None:
        from kubernetes_tpu.apiserver.watchcache import _Frame

        payload = _Frame(event).json_bytes()
        self.frames_encoded += 1
        self.ring.append(event.resource_version, payload)

    # -- lifecycle --

    async def start(self) -> "StoreOwner":
        self._server = await asyncio.start_unix_server(
            self._serve_conn, path=self.rpc_path)
        return self

    async def aclose(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        try:
            self.store.event_taps.remove(self._ring_tap)
        except ValueError:
            pass
        try:
            os.unlink(self.rpc_path)
        except OSError:
            pass
        self.ring.close()
        self.ring.unlink()

    # -- worker liveness --

    def dead_workers(self) -> list[int]:
        """Reader slots whose registered pid no longer exists."""
        out = []
        for i in range(self.ring.n_slots):
            pid, _, _ = self.ring.slot(i)
            if pid and not _pid_alive(pid):
                out.append(i)
        return out

    def reclaim_slot(self, worker_id: int) -> tuple[int, int]:
        """Crash cleanup: clear the dead worker's pid, keep its
        read_pos/last_rv so the respawn resumes without replaying frames
        the dead process already delivered."""
        return self.ring.release_slot(worker_id)

    # -- RPC server --

    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                try:
                    req = json.loads(line)
                except ValueError:
                    return
                resp = self._dispatch(req)
                writer.write(json.dumps(resp).encode() + b"\n")
                await writer.drain()
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    def _dispatch(self, req: dict) -> dict:
        self.rpc_requests += 1
        rid = req.get("id")
        verb = req.get("verb", "")
        handler: Callable[[dict], Any] | None = getattr(
            self, f"_rpc_{verb}", None)
        if handler is None:
            return {"id": rid, "ok": False, "error": "ValueError",
                    "message": f"unknown verb {verb!r}"}
        try:
            return {"id": rid, "ok": True, "result": handler(req)}
        except Exception as e:
            # every store/validation/admission error crosses by class
            # name; the worker rehydrates it — this is how a replayed
            # create answers AlreadyExists and a replayed bind Conflict
            return {"id": rid, "ok": False, "error": type(e).__name__,
                    "message": str(e)}

    # -- verbs --

    def _rpc_ping(self, req: dict) -> dict:
        return {"rv": self.store.resource_version}

    def _rpc_register(self, req: dict) -> dict:
        wid = int(req["worker_id"])
        self.ring.claim_slot(wid, int(req["pid"]))
        return {"slot": wid, "ring": self.ring.name}

    def _rpc_snapshot(self, req: dict) -> dict:
        from kubernetes_tpu.apiserver.http import encode_object

        store = self.store
        objects = [[kind, encode_object(obj)]
                   for kind, bucket in store._objects.items()
                   for obj in bucket.values()]
        history = [[e.type, e.kind, e.resource_version,
                    encode_object(e.obj)] for e in store._history]
        # ring_pos is exact, not racy: the owner loop is single-threaded
        # and the tap appends synchronously inside every mutation, so
        # tail here covers precisely the events up to resource_version
        return {"rv": store.resource_version, "ring_pos": self.ring.tail,
                "min_rv": self.ring.min_rv,
                "objects": objects, "history": history}

    def _rpc_create(self, req: dict) -> dict:
        from kubernetes_tpu.apiserver.http import (decode_object,
                                                   encode_object)

        out = self.store.create(decode_object(req["kind"], req["obj"]))
        return {"rv": self.store.resource_version,
                "obj": encode_object(out)}

    def _rpc_create_many(self, req: dict) -> dict:
        from kubernetes_tpu.apiserver.http import (decode_object,
                                                   encode_object)

        out = self.store.create_many(
            [decode_object(k, o) for k, o in req["objs"]])
        return {"rv": self.store.resource_version,
                "objs": [encode_object(o) for o in out]}

    def _rpc_update(self, req: dict) -> dict:
        from kubernetes_tpu.apiserver.http import (decode_object,
                                                   encode_object)

        out = self.store.update(decode_object(req["kind"], req["obj"]),
                                check_version=req.get("check_version",
                                                      True))
        return {"rv": self.store.resource_version,
                "obj": encode_object(out)}

    def _rpc_delete(self, req: dict) -> dict:
        from kubernetes_tpu.apiserver.http import encode_object

        out = self.store.delete(req["kind"], req["name"],
                                req.get("ns", "default"))
        return {"rv": self.store.resource_version,
                "obj": encode_object(out)}

    def _rpc_patch(self, req: dict) -> dict:
        from kubernetes_tpu.apiserver.http import encode_object

        out = self.store.patch(
            req["kind"], req["name"], req.get("ns", "default"),
            req["patch"],
            req.get("content_type", "application/merge-patch+json"))
        return {"rv": self.store.resource_version,
                "obj": encode_object(out)}

    def _rpc_bind(self, req: dict) -> dict:
        from kubernetes_tpu.apiserver.http import encode_object

        out = self.store.bind(objs.Binding(
            pod_name=req["pod"], namespace=req["ns"],
            target_node=req["node"]))
        return {"rv": self.store.resource_version,
                "obj": encode_object(out)}

    def _rpc_bind_many(self, req: dict) -> dict:
        from kubernetes_tpu.apiserver.http import encode_object

        bindings = [objs.Binding(pod_name=p, namespace=ns, target_node=n)
                    for ns, p, n in req["bindings"]]
        bound, errors = self.store.bind_many(bindings)
        return {
            "rv": self.store.resource_version,
            "bound": [encode_object(o) if o is not None else None
                      for o in bound],
            "errors": [[type(e).__name__, str(e)] if e is not None
                       else None for e in errors],
        }


class RpcClient:
    """Blocking newline-JSON RPC over the owner's unix socket, called
    from the worker's synchronous store verbs (the serving path runs
    store calls synchronously today, so one blocking round-trip here is
    the same latency discipline as the in-process call it replaces).
    Thread-safe; one in-flight request at a time."""

    def __init__(self, path: str, timeout_s: float = 30.0):
        self._path = path
        self._timeout_s = timeout_s
        self._sock: socket.socket | None = None
        self._rfile = None
        self._lock = threading.Lock()
        self._seq = 0

    def _ensure(self) -> None:
        if self._sock is None:
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(self._timeout_s)
            sock.connect(self._path)
            self._sock = sock
            self._rfile = sock.makefile("rb")

    def _reset(self) -> None:
        if self._rfile is not None:
            try:
                self._rfile.close()
            except OSError:
                pass
            self._rfile = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self._reset()

    def call(self, verb: str, **params) -> Any:
        with self._lock:
            self._seq += 1
            data = json.dumps(
                {"id": self._seq, "verb": verb, **params}).encode() + b"\n"
            line = b""
            for attempt in (0, 1):
                try:
                    self._ensure()
                    self._sock.sendall(data)
                    line = self._rfile.readline()
                    if not line:
                        raise ConnectionError("owner closed the RPC socket")
                    break
                except (ConnectionError, OSError):
                    # one reconnect. A torn socket is ambiguous — the verb
                    # may have executed before the tear — but exactly-once
                    # is the STORE's guarantee, not the transport's: the
                    # replay answers AlreadyExists/Conflict, the same
                    # contract RemoteStore documents for failover retries
                    self._reset()
                    if attempt:
                        raise
        resp = json.loads(line)
        if resp.get("ok"):
            return resp.get("result")
        raise _rpc_exception(resp.get("error", ""),
                             resp.get("message", ""))


# ---- worker side ----

def _load_mirror_snapshot(mirror: ObjectStore, snap: dict) -> None:
    """Replace the mirror's state wholesale with an owner snapshot."""
    from kubernetes_tpu.apiserver.http import decode_object

    buckets: dict[str, dict] = {}
    for kind, body in snap["objects"]:
        obj = decode_object(kind, body)
        key = (obj.metadata.namespace or "default", obj.metadata.name)
        buckets.setdefault(kind, {})[key] = obj
        if kind == "Service":
            mirror._reserve_cluster_ip(obj.spec.get("clusterIP", ""))
    mirror._objects = buckets
    mirror._rv = int(snap["rv"])
    mirror._history.clear()
    for etype, kind, rv, body in snap.get("history", []):
        mirror._history.append(
            WatchEvent(etype, kind, decode_object(kind, body), int(rv)))


class RingPump:
    """Worker-side ring consumer. Drains the shared-memory ring on the
    serving loop, applying each record to the mirror store and pushing
    the owner-encoded bytes into the external-feed watch cache. Also
    called synchronously after every forwarded write (`catch_up`) so the
    worker serves read-your-writes. On overrun — the writer lapped this
    reader — takes the honest-410 path: full resync from an owner
    snapshot, every cache subscriber evicted to relist."""

    def __init__(self, ring: EventRing, slot: int, mirror: ObjectStore,
                 cache, rpc: RpcClient, poll_s: float = 0.001):
        self.ring = ring
        self.slot = slot
        self.mirror = mirror
        self.cache = cache
        self.rpc = rpc
        self._poll_s = poll_s
        self._pos = 0
        self.last_rv = 0
        self.applied = 0
        self.resyncs = 0
        self._stopping = False

    def seed(self, ring_pos: int, rv: int) -> None:
        """Set the resume point from an owner snapshot. `last_rv` only
        ratchets up: a respawned worker that inherited a higher last_rv
        from the dead process's slot keeps it, so frames the dead worker
        already delivered are never replayed to clients."""
        self._pos = ring_pos
        self.last_rv = max(self.last_rv, rv)
        self.ring.set_slot(self.slot, read_pos=self._pos,
                           last_rv=self.last_rv)

    def drain(self) -> int:
        """One synchronous drain pass; returns records applied."""
        try:
            pos, records = self.ring.read(self._pos)
        except Expired:
            self.resync()
            return 0
        for rv, payload in records:
            self._apply(rv, payload)
        if records:
            self._pos = pos
            self.ring.set_slot(self.slot, read_pos=self._pos,
                               last_rv=self.last_rv)
        return len(records)

    def catch_up(self, target_rv: int, timeout_s: float = 5.0) -> None:
        """Drain until the mirror covers `target_rv`. The owner appends
        the ring record before answering the RPC, so the bytes are
        already in shared memory — the loop normally completes on the
        first pass without waiting."""
        deadline = time.monotonic() + timeout_s
        while self.last_rv < target_rv:
            if self.drain() == 0:
                if time.monotonic() >= deadline:
                    log.warning("ring catch-up to rv %d stalled at rv %d",
                                target_rv, self.last_rv)
                    return
                # thread-only path: catch_up runs on the RPC caller's
                # thread, never an event loop
                time.sleep(0)  # ktpu: allow[blocking-in-async]

    def _apply(self, rv: int, payload: bytes) -> None:
        if rv <= self.last_rv:
            return  # snapshot overlap / already-delivered (respawn) guard
        from kubernetes_tpu.apiserver.http import decode_object

        d = json.loads(payload)
        body = d["object"]
        obj = decode_object(body.get("kind", ""), body)
        event = WatchEvent(d["type"], obj.kind, obj, rv)
        self.mirror.apply_external_event(event)
        if self.cache is not None:
            self.cache.ingest_external(event, payload)
        self.last_rv = rv
        self.applied += 1

    def resync(self) -> None:
        snap = self.rpc.call("snapshot")
        _load_mirror_snapshot(self.mirror, snap)
        self._pos = int(snap["ring_pos"])
        self.last_rv = int(snap["rv"])
        self.resyncs += 1
        self.ring.set_slot(self.slot, read_pos=self._pos,
                           last_rv=self.last_rv)
        if self.cache is not None:
            self.cache.rebuild_external()

    async def run(self) -> None:
        """Poll task on the serving loop: back-to-back while busy, naps
        while idle."""
        while not self._stopping:
            if self.drain():
                await asyncio.sleep(0)
            else:
                await asyncio.sleep(self._poll_s)

    def stop(self) -> None:
        self._stopping = True


class WorkerStore:
    """Store facade inside a worker process: reads, watches, and the
    serving surface (`_history`, `resource_version`, ...) come from the
    ring-fed mirror via attribute delegation; mutating verbs forward to
    the owner over RPC, then drain the ring to the response's rv so this
    worker immediately reads its own write."""

    def __init__(self, mirror: ObjectStore, rpc: RpcClient,
                 pump: RingPump):
        self.mirror = mirror
        self._rpc = rpc
        self._pump = pump

    def __getattr__(self, name: str) -> Any:
        return getattr(self.mirror, name)

    def _sync(self, res: dict) -> dict:
        self._pump.catch_up(int(res.get("rv", 0)))
        return res

    # -- forwarded verbs --

    def create(self, obj: Any, **_kw) -> Any:
        from kubernetes_tpu.apiserver.http import (decode_object,
                                                   encode_object)

        res = self._sync(self._rpc.call(
            "create", kind=obj.kind, obj=encode_object(obj)))
        return decode_object(obj.kind, res["obj"])

    def create_many(self, objects: list) -> list:
        from kubernetes_tpu.apiserver.http import (decode_object,
                                                   encode_object)

        res = self._sync(self._rpc.call(
            "create_many",
            objs=[[o.kind, encode_object(o)] for o in objects]))
        return [decode_object(d.get("kind", ""), d) for d in res["objs"]]

    def update(self, obj: Any, *, check_version: bool = True) -> Any:
        from kubernetes_tpu.apiserver.http import (decode_object,
                                                   encode_object)

        res = self._sync(self._rpc.call(
            "update", kind=obj.kind, obj=encode_object(obj),
            check_version=check_version))
        return decode_object(obj.kind, res["obj"])

    def delete(self, kind: str, name: str,
               namespace: str = "default") -> Any:
        from kubernetes_tpu.apiserver.http import decode_object

        res = self._sync(self._rpc.call(
            "delete", kind=kind, name=name, ns=namespace))
        return decode_object(kind, res["obj"])

    def patch(self, kind: str, name: str, namespace: str, patch: Any,
              content_type: str = "application/merge-patch+json",
              **_kw) -> Any:
        from kubernetes_tpu.apiserver.http import decode_object

        res = self._sync(self._rpc.call(
            "patch", kind=kind, name=name, ns=namespace, patch=patch,
            content_type=content_type))
        return decode_object(kind, res["obj"])

    def guaranteed_update(self, kind: str, name: str, namespace: str,
                          mutate: Callable[[Any], Any],
                          retries: int = 16) -> Any:
        # the mutate callable can't cross the process boundary: run the
        # CAS loop here against the mirror, retrying on Conflict after
        # draining the ring to the owner's current rv
        last: Exception = Conflict(
            f"{kind} {namespace}/{name}: too many CAS retries")
        for _ in range(max(1, retries)):
            try:
                obj = self.mirror.get(kind, name, namespace)
            except NotFound:
                # mirror may trail a sibling worker's create: catch up
                # to the owner clock once, then let NotFound propagate
                self._pump.catch_up(int(self._rpc.call("ping")["rv"]))
                obj = self.mirror.get(kind, name, namespace)
            replacement = mutate(obj)
            if replacement is not None:
                obj = replacement
            try:
                return self.update(obj)
            except Conflict as e:
                last = e
                self._pump.catch_up(int(self._rpc.call("ping")["rv"]))
        raise last

    def bind(self, binding: Any) -> Any:
        from kubernetes_tpu.apiserver.http import decode_object

        res = self._sync(self._rpc.call(
            "bind", ns=binding.namespace, pod=binding.pod_name,
            node=binding.target_node))
        return decode_object("Pod", res["obj"])

    def bind_many(self, bindings: list) -> tuple[list, list]:
        from kubernetes_tpu.apiserver.http import decode_object

        res = self._sync(self._rpc.call(
            "bind_many",
            bindings=[[b.namespace, b.pod_name, b.target_node]
                      for b in bindings]))
        bound = [decode_object("Pod", d) if d is not None else None
                 for d in res["bound"]]
        errors = [_rpc_exception(e[0], e[1]) if e is not None else None
                  for e in res["errors"]]
        return bound, errors


# ---- worker process entry point ----

@dataclass
class WorkerSpec:
    """Picklable bootstrap config for one worker process. The spawn
    target receives ONLY this — names and numbers, never live handles
    (sockets, loops, stores, shared memory): every handle is constructed
    inside the child (lint R7's discipline)."""

    worker_id: int
    ring_name: str
    rpc_path: str
    host: str = "127.0.0.1"
    port: int = 0  # pre-pick with free_port(): the parent needs it
    shards: int | None = None
    watch_window: int = 4096
    advertise: bool = True
    heartbeat_s: float | None = None
    bench_watchers: int = 0
    bench_kind: str = "Pod"
    poll_s: float = 0.001


def free_port(host: str = "127.0.0.1") -> int:
    """Pre-pick a port for a worker: the parent must know the endpoint
    before the child exists."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def wait_port(host: str, port: int, timeout_s: float = 15.0) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            with socket.create_connection((host, port), timeout=0.25):
                return True
        except OSError:
            # wait_port is called via asyncio.to_thread / from sync
            # harness code only
            time.sleep(0.02)  # ktpu: allow[blocking-in-async]
    return False


def spawn_worker(spec: WorkerSpec):
    """Spawn one worker via the *spawn* context — a forked child would
    inherit the parent's live loop/socket/shm handles (lint R7)."""
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    proc = ctx.Process(target=worker_main, args=(spec,),
                       name=f"ktpu-worker-{spec.worker_id}", daemon=True)
    proc.start()
    return proc


def worker_main(spec: WorkerSpec) -> None:
    """Module-level spawn target of one apiserver worker process."""
    pin_to_core(spec.worker_id)
    try:
        asyncio.run(_worker_serve(spec))
    except KeyboardInterrupt:
        pass


def _attach_bench_sinks(cache, spec: WorkerSpec) -> None:
    """bench[multiproc]'s in-process watcher population: each sink
    touches the frame's wire bytes exactly as the HTTP write path does,
    so delivery counts and the encode-once ledger measure the real
    pipeline without 100k live sockets per worker."""
    if not spec.bench_watchers:
        return

    def sink(frame) -> None:
        frame.json_bytes()

    for _ in range(spec.bench_watchers):
        cache.watch_sink(spec.bench_kind, sink=sink)


async def _worker_serve(spec: WorkerSpec) -> None:
    from kubernetes_tpu.apiserver.http import APIServer
    from kubernetes_tpu.apiserver.watchcache import WatchCache
    from kubernetes_tpu.obs import metrics as obs_metrics

    ring = EventRing.attach(spec.ring_name)
    rpc = RpcClient(spec.rpc_path)
    rpc.call("register", worker_id=spec.worker_id, pid=os.getpid())
    # per-process /metrics identity: every scrape of this worker carries
    # its own `worker` label (each process renders its own registry)
    obs_metrics.REGISTRY.gauge(
        "ktpu_worker_up", "1 while this worker process serves.",
        labels=("worker",)).labels(str(spec.worker_id)).set(1)
    _, _, slot_last_rv = ring.slot(spec.worker_id)
    mirror = ObjectStore(watch_window=spec.watch_window)
    cache = WatchCache(mirror, shards=spec.shards)
    pump = RingPump(ring, spec.worker_id, mirror, cache, rpc,
                    poll_s=spec.poll_s)
    snap = rpc.call("snapshot")
    _load_mirror_snapshot(mirror, snap)
    # respawn resume: the slot's last_rv survives the crash; seed() keeps
    # the max of it and the snapshot rv, so nothing already delivered by
    # the dead process replays
    pump.last_rv = int(slot_last_rv)
    pump.seed(int(snap["ring_pos"]), int(snap["rv"]))
    cache.start_external()
    store = WorkerStore(mirror, rpc, pump)
    server = APIServer(store, host=spec.host, port=spec.port,
                       watch_cache=True,
                       replica_id=f"worker-{spec.worker_id}")
    server.watch_cache = cache  # pre-built, externally fed
    if spec.heartbeat_s is not None:
        server.watch_heartbeat_s = spec.heartbeat_s
    await server.start()
    pump_task = asyncio.get_running_loop().create_task(pump.run())
    if spec.advertise:
        server.advertise()
    _attach_bench_sinks(cache, spec)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, stop.set)
        except (NotImplementedError, ValueError):
            pass
    await stop.wait()
    # graceful exit (SIGTERM): DRAIN every watcher, join shard threads,
    # detach from the ring — the segment's lifetime belongs to the owner
    pump.stop()
    pump_task.cancel()
    try:
        await pump_task
    except asyncio.CancelledError:
        pass
    if spec.advertise:
        try:
            server.unadvertise()
        except Exception:
            pass
    await server.drain(timeout=2.0)
    rpc.close()
    ring.close()
