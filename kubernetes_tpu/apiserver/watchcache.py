"""Watch cache: one store subscription fanned out to N watch subscribers.

The cacher analog (reference apiserver/pkg/storage/cacher/cacher.go):
without it, every HTTP watcher is its own store subscriber, so each
published event costs one store-side queue put per watcher — O(watchers)
work inside the write path. The WatchCache subscribes to the store exactly
ONCE (so 100k watchers cost one store read per event —
`ObjectStore.fanout_puts` is the counter that proves it), keeps its own
ring of recent events plus a latest-object map per kind, and a sharded
delivery plane fans frames out to subscribers OFF the write path.

Delivery plane (PR 13), three pieces:

- **Encode-once frames** (`_Frame`, the caching_object.go analog): each
  ingested event is serialized to its wire frame at most once per format;
  every subscriber shares the immutable bytes, so 1M deliveries pay ~20
  `json.dumps`, not 1M.
- **Shard threads** (`FanoutShard`): N OS worker threads each own a slice
  of subscribers with a per-kind index. The serving loop only ingests from
  the store pump and hands frames to interested shards; queue puts and
  watch-socket writes happen on the shard threads. Thread→loop crossings
  go through `call_soon_threadsafe` only (ktpu-lint R1 tier-3).
- **Per-kind subscriber index**: an event touches only subscribers watching
  its kind (plus all-kinds watchers), not every subscriber on the shard.

`KTPU_FANOUT_SHARDS=0` pins the pre-shard single-loop behavior: fan-out
workers are asyncio tasks on the serving loop (`_Worker`), the fallback
the parity tests diff against.

Slow consumers are absorbed by their bounded queue and evicted when it
overflows — without ever touching the store. A resume point older than the
ring raises `Expired` (HTTP 410), the same Reflector-relist contract as
the store itself. `drain_subscribers` ends every stream with the DRAINED
sentinel instead (resume elsewhere, not relist) — the PR 12 FailoverWatch
contract.

Single-loop discipline for control-plane state: `start()`, `watch()`,
`stop()` and the ingest pump all run on the serving loop; `start()` primes
the ring from the store's own history synchronously, so no event can land
between priming and subscribing.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable

from kubernetes_tpu.apiserver.store import Expired, WatchEvent

log = logging.getLogger(__name__)

# end-of-stream marker for evicted subscribers (same protocol as the store)
_EVICTED = object()
# end-of-stream marker for a graceful replica drain: the stream ends like
# an eviction, but the consumer is told to RESUME from its last rv on
# another replica instead of relisting (CacheWatchStream.drained)
_DRAINED = object()


class SinkClosed(Exception):
    """Raised by a frame sink whose consumer is gone (connection closed):
    the subscriber is detached WITHOUT counting a slow-consumer eviction —
    the evictions counter must keep meaning "slow consumer"."""


def default_shards() -> int:
    """Fan-out shard thread count; `KTPU_FANOUT_SHARDS=0` pins the
    single-loop fallback (asyncio-task workers on the serving loop)."""
    try:
        return max(0, int(os.environ.get("KTPU_FANOUT_SHARDS", "4")))
    except ValueError:
        return 4


_mx = None


def _metrics():
    global _mx
    if _mx is None:
        from kubernetes_tpu.obs import metrics as m

        _mx = (
            m.REGISTRY.counter(
                "watchcache_subscribers_evicted_total",
                "Watch-cache subscribers evicted for exceeding their queue "
                "bound (slow consumers must relist)."),
            m.REGISTRY.counter(
                "watchcache_frames_encoded_total",
                "Watch frames serialized to wire bytes. Encode-once "
                "contract: tracks ingested events, not deliveries."),
            m.REGISTRY.counter(
                "watchcache_frames_delivered_total",
                "Frame deliveries to subscribers (queue puts + sink "
                "calls). delivered/encoded is the fan-out ratio."),
            m.REGISTRY.histogram(
                "watchcache_delivery_seconds",
                "Latency from event ingest to subscriber-queue put / sink "
                "call completion, per frame per shard.",
                buckets=m.exponential_buckets(1e-5, 4.0, 12)),
            m.REGISTRY.gauge(
                "watchcache_shard_queue_high_water",
                "High-water mark of each fan-out shard's dispatch queue.",
                labels=("shard",)),
        )
    return _mx


_encode_object = None


def _encoder():
    # http.py owns the v1 JSON object shape; imported lazily (http.py
    # imports this module lazily too — neither import runs at module load)
    global _encode_object
    if _encode_object is None:
        from kubernetes_tpu.apiserver.http import encode_object

        _encode_object = encode_object
    return _encode_object


class _Frame:
    """One ingested event plus its wire encodings, serialized AT MOST ONCE
    per format (the CachingObject analog): the first delivery in each
    format pays the encode under the frame lock, every other delivery
    shares the immutable bytes. Purely in-process consumers (informers,
    drills) never touch the bytes, so they never pay an encode at all."""

    __slots__ = ("event", "t_ingest", "_json", "_wire", "_lock")

    def __init__(self, event: WatchEvent):
        self.event = event
        self.t_ingest = time.perf_counter()
        self._json: bytes | None = None
        self._wire: bytes | None = None
        self._lock = threading.Lock()

    def json_bytes(self) -> bytes:
        data = self._json
        if data is None:
            with self._lock:
                data = self._json
                if data is None:
                    ev = self.event
                    # byte-for-byte the frame _serve_watch used to build
                    # per delivery: same key order, same trailing newline
                    data = json.dumps(
                        {"type": ev.type,
                         "resourceVersion": ev.resource_version,
                         "object": _encoder()(ev.obj)}).encode() + b"\n"
                    _metrics()[1].inc()
                    self._json = data
        return data

    def wire_bytes(self) -> bytes:
        data = self._wire
        if data is None:
            from kubernetes_tpu.api import wire

            with self._lock:
                data = self._wire
                if data is None:
                    ev = self.event
                    data = wire.encode_watch_frame(
                        ev.type, ev.resource_version, _encoder()(ev.obj))
                    _metrics()[1].inc()
                    self._wire = data
        return data


class _SubQueue:
    """Thread-safe bounded subscriber queue bridging shard threads to a
    loop-side consumer. The consumer parks on an asyncio.Event; a producer
    on any thread wakes it via `call_soon_threadsafe` (the only sanctioned
    thread→loop crossing). Single consumer per queue."""

    __slots__ = ("_buf", "_max", "_lock", "_waiter")

    def __init__(self, maxsize: int):
        self._buf: deque = deque()
        self._max = maxsize
        self._lock = threading.Lock()
        self._waiter: tuple | None = None  # (loop, asyncio.Event)

    def _append(self, item) -> None:
        self._buf.append(item)
        waiter, self._waiter = self._waiter, None
        if waiter is not None:
            loop, event = waiter
            try:
                loop.call_soon_threadsafe(event.set)
            except RuntimeError:
                pass  # consumer's loop already closed (teardown)

    def put_nowait(self, item) -> None:
        with self._lock:
            if self._max and len(self._buf) >= self._max:
                raise asyncio.QueueFull
            self._append(item)

    def put_terminal(self, sentinel) -> None:
        """Enqueue an end-of-stream sentinel, dropping the oldest buffered
        event first when the queue is full — the sentinel must land NOW so
        a consumer blocked in next() learns of eviction promptly instead
        of after draining the whole backlog."""
        with self._lock:
            if self._max and len(self._buf) >= self._max:
                self._buf.popleft()
            self._append(sentinel)

    def empty(self) -> bool:
        return not self._buf

    async def get(self, timeout: float | None = None):
        while True:
            with self._lock:
                if self._buf:
                    return self._buf.popleft()
                event = asyncio.Event()
                self._waiter = (asyncio.get_running_loop(), event)
            try:
                if timeout is None:
                    await event.wait()
                else:
                    await asyncio.wait_for(event.wait(), timeout)
            except asyncio.TimeoutError:
                with self._lock:
                    if self._waiter is not None \
                            and self._waiter[1] is event:
                        self._waiter = None
                raise


class _CacheSub:
    __slots__ = ("kind", "queue", "sink", "on_end", "evicted", "home",
                 "min_rv")

    def __init__(self, kind: str | None, queue: _SubQueue | None,
                 min_rv: int = 0):
        self.kind = kind
        self.queue = queue
        # sink mode: delivery is a direct call on the shard thread
        # (per-watcher goroutine analog) instead of a queue put
        self.sink: Callable[[_Frame], None] | None = None
        self.on_end: Callable[[str], None] | None = None
        self.evicted = False
        self.home: FanoutShard | _Worker | None = None
        # events at or below this rv were already served from the ring
        # backlog (or predate the subscriber's "now"): the fan-out skips
        # them — unlike the store's synchronous subscribe, an event can
        # already be in flight through the pump when a subscriber joins
        self.min_rv = min_rv


class _Worker:
    """Single-loop fan-out shard (`KTPU_FANOUT_SHARDS=0`): its own
    dispatch queue + subscriber slice, delivered by an asyncio task on the
    serving loop — the pre-shard behavior, pinned as the fallback the
    parity tests diff against."""

    __slots__ = ("queue", "subs", "task")

    def __init__(self):
        self.queue: asyncio.Queue = asyncio.Queue()
        self.subs: list[_CacheSub] = []
        self.task: asyncio.Task | None = None

    def add(self, sub: _CacheSub) -> None:
        self.subs.append(sub)

    def discard(self, sub: _CacheSub) -> bool:
        try:
            self.subs.remove(sub)
        except ValueError:
            return False
        return True

    @property
    def sub_count(self) -> int:
        return len(self.subs)

    def all_subs(self) -> list[_CacheSub]:
        return list(self.subs)


class FanoutShard:
    """One fan-out shard: an OS worker thread owning a slice of
    subscribers behind a per-kind index. The serving loop submits
    encoded-once frames; delivery — subscriber-queue puts and watch-socket
    writes — happens here, off the loop. The thread never touches the
    event loop except through `call_soon_threadsafe` (R1 tier-3)."""

    def __init__(self, cache: "WatchCache", index: int):
        self._cache = cache
        self.index = index
        self._cond = threading.Condition()
        self._items: deque = deque()
        self._stopping = False
        self._lock = threading.Lock()  # guards the subscriber index
        self._by_kind: dict[str | None, list[_CacheSub]] = {}
        self._nsubs = 0
        self.high_water = 0
        self.thread: threading.Thread | None = None

    def start(self) -> None:
        self.thread = threading.Thread(
            target=self._run, name=f"ktpu-fanout-{self.index}", daemon=True)
        self.thread.start()

    # ---- loop side ----

    def wants(self, kind: str) -> bool:
        """Cheap lock-free per-kind check on the ingest path. Additions
        happen on the serving loop (same thread as this call), so a
        just-added subscriber can't be missed; a shard-thread eviction
        racing us at worst submits one frame nobody wants."""
        by = self._by_kind
        return bool(by.get(kind)) or bool(by.get(None))

    def add(self, sub: _CacheSub) -> None:
        with self._lock:
            self._by_kind.setdefault(sub.kind, []).append(sub)
            self._nsubs += 1

    def discard(self, sub: _CacheSub) -> bool:
        with self._lock:
            subs = self._by_kind.get(sub.kind)
            if not subs:
                return False
            try:
                subs.remove(sub)
            except ValueError:
                return False
            self._nsubs -= 1
            return True

    @property
    def sub_count(self) -> int:
        return self._nsubs

    def all_subs(self) -> list[_CacheSub]:
        with self._lock:
            return [s for subs in self._by_kind.values() for s in subs]

    def submit(self, frame: _Frame) -> None:
        with self._cond:
            self._items.append((None, frame))
            depth = len(self._items)
            self._cond.notify()
        if depth > self.high_water:
            self.high_water = depth
            _metrics()[4].labels(str(self.index)).set(depth)

    def submit_backlog(self, sub: _CacheSub, frames: list[_Frame]) -> None:
        """Targeted resume-backlog replay, ordered before any broadcast
        frame submitted after it (FIFO queue, all submits on the loop)."""
        with self._cond:
            self._items.append((sub, frames))
            self._cond.notify()

    def stop(self) -> None:
        with self._cond:
            self._stopping = True
            self._items.clear()  # stranded frames drained, not leaked
            self._cond.notify()

    def join(self, timeout: float | None = None) -> None:
        thread = self.thread
        if thread is not None:
            thread.join(timeout)

    # ---- shard thread ----

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._items and not self._stopping:
                    self._cond.wait(timeout=1.0)
                if self._stopping:
                    self._items.clear()
                    return
                target, payload = self._items.popleft()
            if target is None:
                self._broadcast(payload)
            else:
                self._replay(target, payload)

    def _broadcast(self, frame: _Frame) -> None:
        ev = frame.event
        with self._lock:
            subs = list(self._by_kind.get(ev.kind, ()))
            general = self._by_kind.get(None)
            if general:
                subs.extend(general)
        if not subs:
            return
        delivered = 0
        for sub in subs:
            if ev.resource_version <= sub.min_rv:
                continue
            if self._cache._deliver(sub, frame):
                delivered += 1
        if delivered:
            mx = _metrics()
            mx[2].inc(delivered)
            mx[3].observe(time.perf_counter() - frame.t_ingest)

    def _replay(self, sub: _CacheSub, frames: list[_Frame]) -> None:
        delivered = 0
        for frame in frames:
            if sub.evicted:
                break
            if self._cache._deliver(sub, frame):
                delivered += 1
        if delivered:
            _metrics()[2].inc(delivered)


class SinkHandle:
    """Owner-side handle for one sink subscription."""

    __slots__ = ("_cache", "_sub")

    def __init__(self, cache: "WatchCache", sub: _CacheSub):
        self._cache = cache
        self._sub = sub

    @property
    def evicted(self) -> bool:
        return self._sub.evicted

    def stop(self) -> None:
        """Unsubscribe without an end notification — the owner is going
        away on its own terms."""
        home = self._sub.home
        if home is not None:
            home.discard(self._sub)
        self._sub.evicted = True


class WatchCache:
    """Fan-out cache in front of `ObjectStore.watch`.

    `store` may be the raw ObjectStore or any proxy over it (FaultPlane,
    RaceDetector) — the single subscription goes through the proxy, the
    ring priming reads the underlying history."""

    def __init__(self, store: Any, window: int | None = None,
                 workers: int = 4, queue_limit: int | None = None,
                 shards: int | None = None):
        self.store = store
        self._ring: deque[_Frame] = deque(
            maxlen=window or store._history.maxlen or 4096)
        self._latest: dict[str, dict] = {}
        self._queue_limit = store._watcher_queue_limit \
            if queue_limit is None else queue_limit
        self.shards_n = default_shards() if shards is None \
            else max(0, shards)
        self._n_workers = max(1, workers)
        self._shards: list[FanoutShard] = []
        self._workers: list[_Worker] = []
        self._last_rv = 0
        self._stream = None
        self._pump_task: asyncio.Task | None = None
        # cancelled-but-unawaited tasks, reaped by aclose() (cancel
        # without await leaks "Task was destroyed but it is pending")
        self._stashed: list[asyncio.Task] = []
        self._count_lock = threading.Lock()
        self.started = False
        # external-feed mode (multiproc workers): no store subscription —
        # the ring pump pushes pre-encoded frames via ingest_external()
        self._external = False
        # drill/test counters
        self.events_total = 0
        self.evictions = 0
        self.rebuilds = 0

    @property
    def sharded(self) -> bool:
        return bool(self._shards)

    # ---- lifecycle ----

    def start(self) -> "WatchCache":
        """Prime from the store and subscribe — all synchronous on the
        serving loop, so no event lands between priming and subscribing."""
        if self.started:
            return self
        self._ring.clear()  # restart after stop(): re-prime, don't append
        self._ring.extend(_Frame(e) for e in self.store._history)
        self._last_rv = self.store.resource_version
        self._latest = {kind: dict(bucket)
                        for kind, bucket in self.store._objects.items()}
        self._stream = self.store.watch(None)
        loop = asyncio.get_running_loop()
        self._pump_task = loop.create_task(self._pump())
        if self.shards_n:
            # fresh shard objects every start: threads are not reusable
            self._shards = [FanoutShard(self, i)
                            for i in range(self.shards_n)]
            for shard in self._shards:
                shard.start()
        else:
            self._workers = [_Worker() for _ in range(self._n_workers)]
            for w in self._workers:
                w.task = loop.create_task(self._fan_out(w))
        self.started = True
        return self

    def start_external(self) -> "WatchCache":
        """Start in external-feed mode (multiproc worker processes): prime
        ring + latest map from the mirror store, start the delivery plane
        (shards or loop workers), but subscribe to NOTHING — the worker's
        ring pump is the only event source, pushing frames whose wire
        bytes were encoded once in the owner process via
        `ingest_external`. Must run on the serving loop."""
        if self.started:
            return self
        self._ring.clear()
        self._ring.extend(_Frame(e) for e in self.store._history)
        self._last_rv = self.store.resource_version
        self._latest = {kind: dict(bucket)
                        for kind, bucket in self.store._objects.items()}
        self._external = True
        loop = asyncio.get_running_loop()
        if self.shards_n:
            self._shards = [FanoutShard(self, i)
                            for i in range(self.shards_n)]
            for shard in self._shards:
                shard.start()
        else:
            self._workers = [_Worker() for _ in range(self._n_workers)]
            for w in self._workers:
                w.task = loop.create_task(self._fan_out(w))
        self.started = True
        return self

    def ingest_external(self, event: WatchEvent,
                        json_payload: bytes | None = None) -> None:
        """Ingest one externally-published event (the multiproc ring pump
        path, on the serving loop). `json_payload` is the owner-encoded
        wire frame: the frame is pre-populated with it so every delivery
        in this process shares the owner's bytes — zero per-process
        re-encode, and `watchcache_frames_encoded_total` stays 0 here
        (the owner's counter is the encode-once ledger)."""
        frame = _Frame(event)
        if json_payload is not None:
            frame._json = json_payload
        self._ring.append(frame)
        self._last_rv = max(self._last_rv, event.resource_version)
        obj = event.obj
        key = (obj.metadata.namespace or "default", obj.metadata.name)
        bucket = self._latest.setdefault(event.kind, {})
        if event.type == "DELETED":
            bucket.pop(key, None)
        else:
            bucket[key] = obj
        self.events_total += 1
        if self._shards:
            for shard in self._shards:
                if shard.wants(event.kind):
                    shard.submit(frame)
        else:
            for w in self._workers:
                w.queue.put_nowait(frame)

    def rebuild_external(self) -> None:
        """External-feed mode's honest-410 path: the ring overran this
        worker. The pump has already resynced the mirror store from an
        owner snapshot; rebuild the frame ring + latest map from it and
        evict every subscriber — they relist, exactly as if the store
        itself had expired their resume point. Never a silent gap."""
        self._latest = {kind: dict(bucket)
                        for kind, bucket in self.store._objects.items()}
        self._ring.clear()
        self._last_rv = self.store.resource_version
        self.rebuilds += 1
        for sub in self._all_subs():
            self._end_sub(sub, _EVICTED, count=True, reason="evicted")
        log.warning("watch cache (external feed): ring overrun; rebuilt "
                    "from mirror snapshot and evicted all subscribers")

    def stop(self) -> None:
        """Synchronous, idempotent teardown: cancels the pump/worker tasks
        (stashing them for `aclose()` to await), signals shard threads to
        exit (each drains its stranded queue on the way out), and stops
        the store subscription. Safe to call more than once."""
        if self._pump_task is not None:
            self._pump_task.cancel()
            self._stashed.append(self._pump_task)
            self._pump_task = None
        for w in self._workers:
            if w.task is not None:
                w.task.cancel()
                self._stashed.append(w.task)
                w.task = None
            while not w.queue.empty():  # stranded frames
                w.queue.get_nowait()
        for shard in self._shards:
            shard.stop()
        if self._stream is not None:
            self._stream.stop()
            self._stream = None
        self.started = False

    async def aclose(self) -> None:
        """`stop()` plus the awaits it can't do synchronously: reap the
        cancelled pump/worker tasks and join the shard threads."""
        self.stop()
        tasks, self._stashed = self._stashed, []
        for task in tasks:
            try:
                await task
            except asyncio.CancelledError:
                pass
            except Exception:
                log.exception("watch cache task died uncleanly")
        for shard in self._shards:
            if shard.thread is not None:
                await asyncio.to_thread(shard.join, 2.0)

    # ---- the one store subscription ----

    async def _pump(self) -> None:
        while True:
            stream = self._stream
            if stream is None:
                return  # stop() ran while we were ready-to-run; the
                # CancelledError only lands at the next suspension point
            event = await stream.next(timeout=5.0)
            if event is None:
                if getattr(stream, "_stopped", False):
                    await self._resubscribe()
                continue
            self._ingest(event)

    def _ingest(self, event: WatchEvent) -> None:
        # per-kind index inside: the frame only reaches shards with at
        # least one interested subscriber
        self.ingest_external(event)

    async def _resubscribe(self) -> None:
        """The cache's own subscription died (forced expiry / eviction):
        resume from the last seen revision, or — when that point is gone —
        rebuild from a store snapshot and evict every subscriber, who must
        relist exactly as if they had watched the store directly."""
        try:
            self._stream = self.store.watch(None, since=self._last_rv)
            return
        except Expired:
            pass
        self._latest = {kind: dict(bucket)
                        for kind, bucket in self.store._objects.items()}
        self._ring.clear()
        self._last_rv = self.store.resource_version
        self._stream = self.store.watch(None)
        self.rebuilds += 1
        for sub in self._all_subs():
            self._end_sub(sub, _EVICTED, count=True, reason="evicted")
        log.warning("watch cache: resume point expired; rebuilt from "
                    "store snapshot and evicted all subscribers")

    # ---- fan-out ----

    async def _fan_out(self, worker: _Worker) -> None:
        while True:
            frame = await worker.queue.get()
            ev = frame.event
            delivered = 0
            for sub in list(worker.subs):
                if ev.resource_version <= sub.min_rv:
                    continue
                if sub.kind is None or sub.kind == ev.kind:
                    if self._deliver(sub, frame):
                        delivered += 1
            if delivered:
                mx = _metrics()
                mx[2].inc(delivered)
                mx[3].observe(time.perf_counter() - frame.t_ingest)

    def _deliver(self, sub: _CacheSub, frame: _Frame) -> bool:
        """One delivery attempt — shard thread or loop, either mode. A
        failed attempt ends the subscription (evict or detach) inline."""
        if sub.sink is not None:
            try:
                sub.sink(frame)
                return True
            except SinkClosed:
                self._end_sub(sub, _EVICTED, count=False, reason="closed")
                return False
            except Exception:
                self._end_sub(sub, _EVICTED, count=True, reason="evicted")
                return False
        try:
            sub.queue.put_nowait(frame)
            return True
        except asyncio.QueueFull:
            self._end_sub(sub, _EVICTED, count=True, reason="evicted")
            return False

    def _end_sub(self, sub: _CacheSub, sentinel, count: bool,
                 reason: str) -> None:
        """Terminate one subscription (thread-safe): unsubscribe, mark
        evicted, enqueue the end-of-stream sentinel — dropping the oldest
        buffered event first if the queue is full, so a consumer blocked
        in next() learns its fate promptly — and notify any sink."""
        home = sub.home
        if home is None or not home.discard(sub):
            return  # already ended/stopped
        sub.evicted = True
        if sub.queue is not None:
            sub.queue.put_terminal(sentinel)
        if count:
            with self._count_lock:
                self.evictions += 1
            _metrics()[0].inc()
        if sub.on_end is not None:
            try:
                sub.on_end(reason)
            except Exception:
                log.exception("watch sink on_end callback failed")

    def drain_subscribers(self) -> None:
        """Graceful replica shutdown: end every subscription with the
        DRAINED sentinel (wakes consumers blocked in next() immediately).
        Not an eviction — subscribers resume from their last rv on another
        replica rather than relisting."""
        for sub in self._all_subs():
            self._end_sub(sub, _DRAINED, count=False, reason="drained")

    def _all_subs(self) -> list[_CacheSub]:
        out: list[_CacheSub] = []
        for w in self._workers:
            out.extend(w.all_subs())
        for shard in self._shards:
            out.extend(shard.all_subs())
        return out

    # ---- reads ----

    def get_cached(self, kind: str, name: str,
                   namespace: str = "default") -> Any | None:
        """Latest object the cache has seen (read-only; may trail the
        store by in-flight fan-out)."""
        return self._latest.get(kind, {}).get((namespace or "default", name))

    def _resume_backlog(self, kind: str | None,
                        since: int | None) -> list[_Frame]:
        backlog: list[_Frame] = []
        if since is not None and since < self._last_rv:
            oldest = self._ring[0].event.resource_version if self._ring \
                else self._last_rv + 1
            if since < oldest - 1:
                raise Expired(f"resourceVersion {since} is too old "
                              f"(cache window starts at {oldest})")
            backlog = [f for f in self._ring
                       if f.event.resource_version > since
                       and (kind is None or kind == f.event.kind)]
        if self._queue_limit and len(backlog) >= self._queue_limit:
            raise Expired(
                f"resume backlog of {len(backlog)} events exceeds the "
                f"{self._queue_limit}-event subscriber bound")
        return backlog

    def _min_rv(self, since: int | None) -> int:
        # max(since, _last_rv), NOT bare `since`: the ring backlog covers
        # (since, _last_rv], and an event in that range can also already
        # be in flight through a shard/worker queue — bare `since` would
        # deliver it twice
        return self._last_rv if since is None else max(since, self._last_rv)

    def watch(self, kind: str | None = None,
              since: int | None = None) -> "CacheWatchStream":
        """Subscribe through the cache — the `ObjectStore.watch` contract
        (backlog from the ring, Expired when the resume point predates it),
        but the subscriber costs the store nothing."""
        backlog = self._resume_backlog(kind, since)
        sub = _CacheSub(kind, _SubQueue(self._queue_limit),
                        min_rv=self._min_rv(since))
        home = self._least_loaded()
        sub.home = home
        home.add(sub)
        # direct puts are safe in both modes: subscribe runs on the loop,
        # so no broadcast with rv > min_rv can be enqueued before these
        for frame in backlog:
            sub.queue.put_nowait(frame)  # bound pre-checked via Expired
        if backlog:
            _metrics()[2].inc(len(backlog))
        return CacheWatchStream(sub)

    def watch_sink(self, kind: str | None = None,
                   since: int | None = None, *,
                   sink: Callable[[_Frame], None],
                   on_end: Callable[[str], None] | None = None
                   ) -> SinkHandle:
        """Subscribe a frame sink: delivery is a direct `sink(frame)` call
        on the owning shard thread (the per-watcher goroutine analog) — no
        subscriber queue, no loop hop. The sink must be thread-safe, must
        not touch the event loop except via `call_soon_threadsafe`, and
        signals a dead consumer by raising SinkClosed (detached, not
        counted); any other exception evicts (slow consumer). The resume
        backlog replays on the shard thread, ordered before live frames."""
        backlog = self._resume_backlog(kind, since)
        sub = _CacheSub(kind, None, min_rv=self._min_rv(since))
        sub.sink = sink
        sub.on_end = on_end
        home = self._least_loaded()
        sub.home = home
        home.add(sub)
        if backlog:
            if isinstance(home, FanoutShard):
                home.submit_backlog(sub, backlog)
            else:
                # single-loop fallback: replay inline (tests only — the
                # HTTP path never uses sinks without shards)
                delivered = 0
                for frame in backlog:
                    if sub.evicted or not self._deliver(sub, frame):
                        break
                    delivered += 1
                if delivered:
                    _metrics()[2].inc(delivered)
        return SinkHandle(self, sub)

    def _least_loaded(self):
        return min(self._shards or self._workers,
                   key=lambda home: home.sub_count)

    @property
    def subscriber_count(self) -> int:
        return sum(w.sub_count for w in self._workers) \
            + sum(s.sub_count for s in self._shards)


class CacheWatchStream:
    """WatchStream-compatible consumer side of one cache subscription."""

    def __init__(self, sub: _CacheSub):
        self._sub = sub
        self._stopped = False
        # True when the stream ended because the replica drained (resume
        # elsewhere) rather than because this consumer was evicted (relist)
        self.drained = False

    async def next(self, timeout: float | None = None) -> WatchEvent | None:
        if self._stopped:
            return None
        sub = self._sub
        if sub.evicted and sub.queue.empty():
            self._stopped = True
            return None
        try:
            item = await sub.queue.get(timeout)
        except asyncio.TimeoutError:
            return None
        if item is _DRAINED:
            self._stopped = True
            self.drained = True
            return None
        if item is _EVICTED:
            self._stopped = True  # stream over: the consumer must relist
            return None
        return item.event

    def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        home = self._sub.home
        if home is not None:
            home.discard(self._sub)

    def __aiter__(self):
        return self

    async def __anext__(self) -> WatchEvent:
        ev = await self.next()
        if ev is None:
            raise StopAsyncIteration
        return ev
