"""Watch cache: one store subscription fanned out to N watch subscribers.

The cacher analog (reference apiserver/pkg/storage/cacher.go): without it,
every HTTP watcher is its own store subscriber, so each published event
costs one store-side queue put per watcher — O(watchers) work inside the
write path. The WatchCache subscribes to the store exactly ONCE (so 10k
watchers cost one store read per event — `ObjectStore.fanout_puts` is the
counter that proves it), keeps its own ring of recent events plus a
latest-object map per kind, and dedicated fan-out worker tasks deliver to
subscriber queues OFF the write path. Slow consumers are absorbed by their
bounded queue and evicted when it overflows — without ever touching the
store. A resume point older than the ring raises `Expired` (HTTP 410), the
same Reflector-relist contract as the store itself.

Single-loop discipline: everything here runs on the serving loop; `start()`
primes the ring from the store's own history synchronously, so no event can
land between priming and subscribing.
"""

from __future__ import annotations

import asyncio
import logging
from collections import deque
from typing import Any

from kubernetes_tpu.apiserver.store import Expired, WatchEvent

log = logging.getLogger(__name__)

# end-of-stream marker for evicted subscribers (same protocol as the store)
_EVICTED = object()
# end-of-stream marker for a graceful replica drain: the stream ends like
# an eviction, but the consumer is told to RESUME from its last rv on
# another replica instead of relisting (CacheWatchStream.drained)
_DRAINED = object()

_mx_evicted = None


def _cache_evictions():
    global _mx_evicted
    if _mx_evicted is None:
        from kubernetes_tpu.obs import metrics as m

        _mx_evicted = m.REGISTRY.counter(
            "watchcache_subscribers_evicted_total",
            "Watch-cache subscribers evicted for exceeding their queue "
            "bound (slow consumers must relist).")
    return _mx_evicted


class _CacheSub:
    __slots__ = ("kind", "queue", "evicted", "worker", "min_rv")

    def __init__(self, kind: str | None, maxsize: int, min_rv: int = 0):
        self.kind = kind
        self.queue: asyncio.Queue = asyncio.Queue(maxsize)
        self.evicted = False
        self.worker: _Worker | None = None
        # events at or below this rv were already served from the ring
        # backlog (or predate the subscriber's "now"): the fan-out skips
        # them — unlike the store's synchronous subscribe, an event can
        # already be in flight through the pump when a subscriber joins
        self.min_rv = min_rv


class _Worker:
    """One fan-out shard: its own dispatch queue + subscriber slice."""

    __slots__ = ("queue", "subs", "task")

    def __init__(self):
        self.queue: asyncio.Queue = asyncio.Queue()
        self.subs: list[_CacheSub] = []
        self.task: asyncio.Task | None = None


class WatchCache:
    """Fan-out cache in front of `ObjectStore.watch`.

    `store` may be the raw ObjectStore or any proxy over it (FaultPlane,
    RaceDetector) — the single subscription goes through the proxy, the
    ring priming reads the underlying history."""

    def __init__(self, store: Any, window: int | None = None,
                 workers: int = 4, queue_limit: int | None = None):
        self.store = store
        self._ring: deque[WatchEvent] = deque(
            maxlen=window or store._history.maxlen or 4096)
        self._latest: dict[str, dict] = {}
        self._queue_limit = store._watcher_queue_limit \
            if queue_limit is None else queue_limit
        self._workers = [_Worker() for _ in range(max(1, workers))]
        self._last_rv = 0
        self._stream = None
        self._pump_task: asyncio.Task | None = None
        self.started = False
        # drill/test counters
        self.events_total = 0
        self.evictions = 0
        self.rebuilds = 0

    # ---- lifecycle ----

    def start(self) -> "WatchCache":
        """Prime from the store and subscribe — all synchronous on the
        serving loop, so no event lands between priming and subscribing."""
        if self.started:
            return self
        self._ring.extend(self.store._history)
        self._last_rv = self.store.resource_version
        self._latest = {kind: dict(bucket)
                        for kind, bucket in self.store._objects.items()}
        self._stream = self.store.watch(None)
        loop = asyncio.get_running_loop()
        self._pump_task = loop.create_task(self._pump())
        for w in self._workers:
            w.task = loop.create_task(self._fan_out(w))
        self.started = True
        return self

    def stop(self) -> None:
        if self._pump_task is not None:
            self._pump_task.cancel()
            self._pump_task = None
        for w in self._workers:
            if w.task is not None:
                w.task.cancel()
                w.task = None
        if self._stream is not None:
            self._stream.stop()
            self._stream = None
        self.started = False

    # ---- the one store subscription ----

    async def _pump(self) -> None:
        while True:
            stream = self._stream
            if stream is None:
                return  # stop() ran while we were ready-to-run; the
                # CancelledError only lands at the next suspension point
            event = await stream.next(timeout=5.0)
            if event is None:
                if getattr(stream, "_stopped", False):
                    await self._resubscribe()
                continue
            self._ingest(event)

    def _ingest(self, event: WatchEvent) -> None:
        self._ring.append(event)
        self._last_rv = max(self._last_rv, event.resource_version)
        obj = event.obj
        key = (obj.metadata.namespace or "default", obj.metadata.name)
        bucket = self._latest.setdefault(event.kind, {})
        if event.type == "DELETED":
            bucket.pop(key, None)
        else:
            bucket[key] = obj
        self.events_total += 1
        for w in self._workers:
            w.queue.put_nowait(event)

    async def _resubscribe(self) -> None:
        """The cache's own subscription died (forced expiry / eviction):
        resume from the last seen revision, or — when that point is gone —
        rebuild from a store snapshot and evict every subscriber, who must
        relist exactly as if they had watched the store directly."""
        try:
            self._stream = self.store.watch(None, since=self._last_rv)
            return
        except Expired:
            pass
        self._latest = {kind: dict(bucket)
                        for kind, bucket in self.store._objects.items()}
        self._ring.clear()
        self._last_rv = self.store.resource_version
        self._stream = self.store.watch(None)
        self.rebuilds += 1
        for w in self._workers:
            for sub in list(w.subs):
                self._evict(sub)
        log.warning("watch cache: resume point expired; rebuilt from "
                    "store snapshot and evicted all subscribers")

    # ---- fan-out ----

    async def _fan_out(self, worker: _Worker) -> None:
        while True:
            event = await worker.queue.get()
            for sub in list(worker.subs):
                if event.resource_version <= sub.min_rv:
                    continue
                if sub.kind is None or sub.kind == event.kind:
                    try:
                        sub.queue.put_nowait(event)
                    except asyncio.QueueFull:
                        self._evict(sub)

    def _evict(self, sub: _CacheSub) -> None:
        worker = sub.worker
        if worker is None:
            return
        try:
            worker.subs.remove(sub)
        except ValueError:
            return  # already evicted/stopped
        sub.evicted = True
        try:
            sub.queue.put_nowait(_EVICTED)
        except asyncio.QueueFull:
            pass  # a full queue can't block in get(): the flag suffices
        self.evictions += 1
        _cache_evictions().inc()

    def drain_subscribers(self) -> None:
        """Graceful replica shutdown: end every subscription with the
        DRAINED sentinel (wakes consumers blocked in next() immediately).
        Not an eviction — subscribers resume from their last rv on another
        replica rather than relisting."""
        for w in self._workers:
            for sub in list(w.subs):
                w.subs.remove(sub)
                sub.evicted = True
                try:
                    sub.queue.put_nowait(_DRAINED)
                except asyncio.QueueFull:
                    pass

    # ---- reads ----

    def get_cached(self, kind: str, name: str,
                   namespace: str = "default") -> Any | None:
        """Latest object the cache has seen (read-only; may trail the
        store by in-flight fan-out)."""
        return self._latest.get(kind, {}).get((namespace or "default", name))

    def watch(self, kind: str | None = None,
              since: int | None = None) -> "CacheWatchStream":
        """Subscribe through the cache — the `ObjectStore.watch` contract
        (backlog from the ring, Expired when the resume point predates it),
        but the subscriber costs the store nothing."""
        backlog: list[WatchEvent] = []
        if since is not None and since < self._last_rv:
            oldest = self._ring[0].resource_version if self._ring \
                else self._last_rv + 1
            if since < oldest - 1:
                raise Expired(f"resourceVersion {since} is too old "
                              f"(cache window starts at {oldest})")
            backlog = [e for e in self._ring
                       if e.resource_version > since
                       and (kind is None or kind == e.kind)]
        if self._queue_limit and len(backlog) >= self._queue_limit:
            raise Expired(
                f"resume backlog of {len(backlog)} events exceeds the "
                f"{self._queue_limit}-event subscriber bound")
        sub = _CacheSub(kind, self._queue_limit,
                        min_rv=self._last_rv if since is None else since)
        worker = min(self._workers, key=lambda w: len(w.subs))
        sub.worker = worker
        worker.subs.append(sub)
        for e in backlog:
            sub.queue.put_nowait(e)
        return CacheWatchStream(sub)

    @property
    def subscriber_count(self) -> int:
        return sum(len(w.subs) for w in self._workers)


class CacheWatchStream:
    """WatchStream-compatible consumer side of one cache subscription."""

    def __init__(self, sub: _CacheSub):
        self._sub = sub
        self._stopped = False
        # True when the stream ended because the replica drained (resume
        # elsewhere) rather than because this consumer was evicted (relist)
        self.drained = False

    async def next(self, timeout: float | None = None) -> WatchEvent | None:
        if self._stopped:
            return None
        if self._sub.evicted and self._sub.queue.empty():
            self._stopped = True
            return None
        try:
            if timeout is None:
                ev = await self._sub.queue.get()
            else:
                ev = await asyncio.wait_for(self._sub.queue.get(), timeout)
        except asyncio.TimeoutError:
            return None
        if ev is _DRAINED:
            self._stopped = True
            self.drained = True
            return None
        if ev is _EVICTED:
            self._stopped = True  # stream over: the consumer must relist
            return None
        return ev

    def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        worker = self._sub.worker
        if worker is not None:
            try:
                worker.subs.remove(self._sub)
            except ValueError:
                pass

    def __aiter__(self):
        return self

    async def __anext__(self) -> WatchEvent:
        ev = await self.next()
        if ev is None:
            raise StopAsyncIteration
        return ev
